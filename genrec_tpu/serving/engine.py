"""In-process online inference engine: queue -> micro-batch -> executable.

The request path (ROADMAP north star: "serves heavy traffic"):

1. `submit(Request)` enqueues into the head's queue and returns a Future.
2. The batcher thread flushes a queue when it holds `max_batch` requests
   OR its oldest request has waited `max_wait_ms` (dynamic micro-batching:
   full batches under load, bounded latency when idle).
3. The micro-batch is padded UP to a (batch, history) bucket from the
   `BucketLadder` and dispatched to the executable AOT-compiled for that
   bucket at warmup — steady state never compiles (the engine counts
   compiles; scripts/check_serving_hlo.py asserts zero after warmup).
4. Outputs are split per-request, futures resolve, and queue-wait /
   compute / total latencies land in the metrics histograms.

Hot checkpoint reload: a watcher thread polls a checkpoint directory of
params-only steps (published by the trainer or a sidecar) and restores
strictly NEWER steps through `CheckpointManager.restore_latest_valid` —
the PR-3 integrity ladder, so a half-written or garbled step is
quarantined and the engine keeps serving the previous valid params. The
restored tree is staged and swapped in by the batcher BETWEEN
micro-batches (never mid-batch), so every request is answered by exactly
one params version, reported as `Response.params_step`.

Graceful drain: a one-shot `PreemptionGuard` latches SIGTERM/SIGINT.
On fire the engine finishes every in-flight and queued request, rejects
new submissions with the typed `DrainingError`, and stops; a second
signal falls through to the restored previous handlers (the PR-3
one-shot escalation contract).

Compiled executables are AOT (`jax.jit(fn).lower(...).compile()`), so a
shape drifting out of the bucket grid raises loudly instead of silently
recompiling; the params swap keeps avals identical (same tree, same
shapes/dtypes), which `_check_like` verifies before staging.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import jax
import numpy as np

from genrec_tpu.core import chaos
from genrec_tpu.serving.buckets import BucketLadder, default_ladder
from genrec_tpu.serving.metrics import ServingMetrics
from genrec_tpu.serving.types import (
    DrainingError,
    Request,
    Response,
    UnknownHeadError,
)


class ServingEngine:
    def __init__(
        self,
        heads: Sequence,
        params,
        *,
        ladder: Optional[BucketLadder] = None,
        max_batch: int = 16,
        max_wait_ms: float = 4.0,
        ckpt_dir: Optional[str] = None,
        ckpt_poll_secs: float = 2.0,
        params_step: Optional[int] = None,
        params_by_head: Optional[bool] = None,
        handle_signals: bool = True,
        guard=None,
        logger: Optional[logging.Logger] = None,
    ):
        self._heads = {h.name: h for h in heads}
        if len(self._heads) != len(heads):
            raise ValueError("duplicate head names")
        self._params = params
        # Multi-head engines serve ONE combined tree {head_name: subtree}
        # so a hot reload swaps every head's params in the same atomic
        # step; a single-head engine may pass its raw tree.
        self._params_by_head = (
            params_by_head if params_by_head is not None else len(self._heads) > 1
        )
        if self._params_by_head:
            missing = [n for n in self._heads if n not in params]
            if missing:
                raise ValueError(f"params missing head subtrees: {missing}")
        self._step = params_step
        self._ladder = ladder or default_ladder(max_batch=max_batch)
        if max_batch > self._ladder.max_batch:
            raise ValueError(
                f"max_batch {max_batch} exceeds largest batch bucket "
                f"{self._ladder.max_batch}"
            )
        self._max_batch = max_batch
        self._max_wait_s = max_wait_ms / 1e3
        self._ckpt_dir = ckpt_dir
        self._ckpt_poll_secs = ckpt_poll_secs
        self._handle_signals = handle_signals
        self._guard = guard
        self._log = logger or logging.getLogger("genrec_tpu")

        self.metrics = ServingMetrics()
        self._exec: dict[tuple[str, int, int], object] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues = {name: collections.deque() for name in self._heads}
        self._pending_params = None  # (tree, step) staged by the watcher
        self._rr = 0  # round-robin head cursor (_next_batch)
        self._draining = False
        self._stop_watch = threading.Event()
        self._drained = threading.Event()
        self._batcher: Optional[threading.Thread] = None
        self._watcher: Optional[threading.Thread] = None
        self._ckpt_mgr = None
        self._started = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingEngine":
        """Refresh head tables, compile every bucket, start the threads,
        install the signal guard. Returns self."""
        if self._started:
            raise RuntimeError("engine already started")
        for head in self._heads.values():
            head.on_params(self._select(head, self._params))
        self.warmup()
        if self._guard is None and self._handle_signals:
            from genrec_tpu.core.preemption import PreemptionGuard

            self._guard = PreemptionGuard(self._log)
        if self._ckpt_dir is not None:
            from genrec_tpu.core.checkpoint import CheckpointManager

            self._ckpt_mgr = CheckpointManager(self._ckpt_dir)
            self._watcher = threading.Thread(
                target=self._watch_loop, name="serving-ckpt-watcher", daemon=True
            )
            self._watcher.start()
        self._batcher = threading.Thread(
            target=self._batch_loop, name="serving-batcher", daemon=True
        )
        self._started = True
        self._batcher.start()
        return self

    def warmup(self) -> None:
        """AOT-compile every (head, batch-bucket, history-bucket) combo so
        steady state is pure executable lookup."""
        t0 = time.monotonic()
        for head in self._heads.values():
            for B, L in self._ladder.combos():
                self._compile(head, B, L)
        self.metrics.mark_warm()
        self._log.info(
            f"serving warmup: {self.metrics.warmup_compiles} executables "
            f"({len(self._heads)} heads x {len(list(self._ladder.combos()))} "
            f"buckets) in {time.monotonic() - t0:.1f}s"
        )

    def stop(self, timeout: float = 60.0) -> dict:
        """Drain (finish queued work, reject new) and join the threads.
        Returns the final metrics snapshot. Idempotent."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        self._stop_watch.set()
        if self._batcher is not None:
            self._batcher.join(timeout)
        if self._watcher is not None:
            self._watcher.join(timeout)
        if self._guard is not None:
            self._guard.close()
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.close()
            self._ckpt_mgr = None
        return self.stats()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block until the engine has fully drained (e.g. after SIGTERM).
        True if drained within timeout."""
        return self._drained.wait(timeout)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def params_step(self) -> Optional[int]:
        return self._step

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["params_step"] = self._step
        snap["draining"] = self._draining
        return snap

    # -- request path --------------------------------------------------------

    def submit(self, req: Request) -> Future:
        if req.head not in self._heads:
            raise UnknownHeadError(
                f"unknown head {req.head!r}; have {sorted(self._heads)}"
            )
        # Per-request validation BEFORE enqueueing: a malformed history
        # raises to its own caller here instead of failing the whole
        # micro-batch it would have been padded into.
        self._heads[req.head].validate(req)
        with self._lock:
            if self._draining:
                self.metrics.record_reject()
                raise DrainingError(
                    "engine is draining (shutdown signal received); "
                    "request rejected — fail over to another replica"
                )
            entry = (req, Future(), time.monotonic())
            self._queues[req.head].append(entry)
            self._work.notify()
        self.metrics.record_submit()
        return entry[1]

    def serve(self, req: Request, timeout: Optional[float] = 60.0) -> Response:
        """Synchronous convenience wrapper around submit()."""
        return self.submit(req).result(timeout)

    # -- batcher -------------------------------------------------------------

    def _batch_loop(self) -> None:
        try:
            while True:
                try:
                    if (
                        self._guard is not None
                        and self._guard.fired
                        and not self._draining
                    ):
                        with self._lock:
                            self._draining = True
                        self._log.warning(
                            "serving: shutdown signal latched — draining "
                            "in-flight requests, rejecting new submissions"
                        )
                    self._apply_pending_params()
                    batch = self._next_batch()
                    if batch is not None:
                        self._run_batch(*batch)
                        continue
                    with self._lock:
                        empty = all(not q for q in self._queues.values())
                        if self._draining and empty:
                            break
                        # Wake on submit/stop notify; when requests are
                        # queued, cap the wait so deadline flushes stay
                        # responsive — when idle, back off (guard/drain
                        # polls tolerate 50ms; a 1 kHz idle spin does not).
                        self._work.wait(
                            timeout=max(self._max_wait_s / 4, 1e-3)
                            if not empty
                            else 0.05
                        )
                except Exception:  # noqa: BLE001 — the batcher must survive
                    # Anything escaping _run_batch's own guard (params
                    # refresh, metrics, future bookkeeping) would otherwise
                    # kill the thread while submit() keeps accepting.
                    self._log.exception("serving: batcher iteration failed")
        finally:
            self._drained.set()

    def _next_batch(self):
        """Pop the next flush-ready head queue: full micro-batch, oldest
        entry past the wait deadline, or draining (flush ASAP). Heads are
        scanned round-robin from just past the last-flushed one, so a
        head under sustained full-batch load cannot starve the others."""
        now = time.monotonic()
        names = list(self._queues)
        with self._lock:
            for i in range(len(names)):
                name = names[(self._rr + i) % len(names)]
                q = self._queues[name]
                if not q:
                    continue
                if (
                    len(q) >= self._max_batch
                    or self._draining
                    or now - q[0][2] >= self._max_wait_s
                ):
                    self._rr = (self._rr + i + 1) % len(names)
                    n = min(len(q), self._max_batch)
                    return self._heads[name], [q.popleft() for _ in range(n)]
        return None

    def _run_batch(self, head, entries) -> None:
        t_start = time.monotonic()
        reqs = [e[0] for e in entries]
        L_nat = max((head.natural_len(r) for r in reqs), default=1)
        L = self._ladder.history_bucket(max(L_nat, 1))
        B = self._ladder.batch_bucket(len(reqs))
        try:
            args = head.make_batch(reqs, B, L)
            compiled = self._get_executable(head, B, L)
            out = compiled(self._select(head, self._params), *args)
            out = jax.tree_util.tree_map(np.asarray, out)  # host sync
            t_done = time.monotonic()
            payloads = head.finalize(out, reqs)
        except Exception as e:  # noqa: BLE001 — a bad batch must not kill the loop
            self._log.exception(f"serving: micro-batch on head {head.name} failed")
            for _, fut, _t in entries:
                if not fut.done():
                    fut.set_exception(e)
            self.metrics.record_failure(len(entries))
            return
        self.metrics.record_batch(head.name, (B, L))
        # Chaos hook (no-op without an installed plan): deliver a real
        # shutdown signal after the Nth micro-batch — the drain chaos test
        # fires SIGTERM mid-load exactly like a preemption would.
        chaos.maybe_kill(step=self.metrics.batches)
        step = self._step
        for (req, fut, t_enq), payload in zip(entries, payloads):
            now = time.monotonic()
            resp = Response(
                head=head.name,
                items=payload["items"],
                scores=payload["scores"],
                sem_ids=payload.get("sem_ids"),
                params_step=step,
                bucket=(B, L),
                queue_wait_s=t_start - t_enq,
                compute_s=t_done - t_start,
                total_s=now - t_enq,
            )
            self.metrics.record_response(
                resp.queue_wait_s, resp.compute_s, resp.total_s
            )
            if not fut.done():  # a cancelled Future must not kill the loop
                fut.set_result(resp)

    def _select(self, head, params):
        return params[head.name] if self._params_by_head else params

    def _get_executable(self, head, B: int, L: int):
        key = (head.name, B, L)
        compiled = self._exec.get(key)
        if compiled is None:
            # Off-ladder shape (should not happen: the ladder covers every
            # reachable bucket). Count it — check_serving_hlo pins zero.
            compiled = self._compile(head, B, L)
        return compiled

    def _compile(self, head, B: int, L: int):
        fn = head.make_fn(B, L)
        args = head.make_batch([head.dummy_request()], B, L)
        compiled = jax.jit(fn).lower(self._select(head, self._params), *args).compile()
        self._exec[(head.name, B, L)] = compiled
        self.metrics.record_compile()
        return compiled

    # -- hot checkpoint reload -----------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop_watch.wait(self._ckpt_poll_secs):
            try:
                self._check_reload()
            except Exception:  # noqa: BLE001 — keep serving on watcher errors
                self._log.exception("serving: checkpoint watcher pass failed")

    def _check_reload(self) -> None:
        mgr = self._ckpt_mgr
        if mgr is None:
            return
        mgr.reload()  # pick up steps written by another process
        latest = mgr.latest_step()
        if latest is None or (self._step is not None and latest <= self._step):
            return
        # Integrity ladder: a garbled newest step is quarantined and the
        # previous valid one returned — which is the step already being
        # served, so the swap below is skipped and serving never pauses.
        restored, step = mgr.restore_latest_valid(self._params)
        if restored is None or (self._step is not None and step <= self._step):
            return
        self._check_like(restored)
        with self._lock:
            self._pending_params = (restored, step)
        self._log.info(f"serving: staged hot reload to checkpoint step {step}")

    def _check_like(self, restored) -> None:
        """The swapped tree must keep every aval identical, or the AOT
        executables would reject it mid-flight. Attribute reads only —
        no device-to-host copies of the weights."""
        cur = jax.tree_util.tree_leaves(self._params)
        new = jax.tree_util.tree_leaves(restored)
        if len(cur) != len(new) or any(
            np.shape(a) != np.shape(b) or np.result_type(a) != np.result_type(b)
            for a, b in zip(cur, new)
        ):
            raise RuntimeError("restored params tree does not match the serving tree")

    def _apply_pending_params(self) -> None:
        """Atomic swap BETWEEN micro-batches (batcher thread only)."""
        with self._lock:
            pending = self._pending_params
            self._pending_params = None
        if pending is None:
            return
        restored, step = pending
        self._params = restored
        self._step = step
        self.metrics.record_swap()
        for head in self._heads.values():
            head.on_params(self._select(head, restored))
        self._log.info(f"serving: now serving checkpoint step {step}")
