"""Bucketed compilation ladder for shape-stable serving.

XLA compiles one executable per input shape. An online engine that padded
each micro-batch to its exact (n_requests, max_history) would compile a
fresh program for nearly every batch — multi-second stalls in the serving
path. The ladder instead rounds both axes UP to a small fixed set of
buckets (Ragged Paged Attention, arxiv 2604.15464, makes the same move
for its paged decode shapes): every bucket combination is compiled once
at warmup, and steady state is pure executable lookup — the engine's
recompilation counter pins it at zero (scripts/check_serving_hlo.py).
"""

from __future__ import annotations

from typing import Sequence


def _validated(name: str, buckets: Sequence[int]) -> tuple[int, ...]:
    out = tuple(int(b) for b in buckets)
    if not out or any(b <= 0 for b in out) or list(out) != sorted(set(out)):
        raise ValueError(
            f"{name} must be strictly increasing positive ints, got {buckets}"
        )
    return out


class BucketLadder:
    """Fixed (batch, history) bucket grids shared by every head."""

    def __init__(
        self,
        batch_buckets: Sequence[int] = (1, 2, 4, 8, 16),
        history_buckets: Sequence[int] = (8, 16, 32, 64),
    ):
        self.batch_buckets = _validated("batch_buckets", batch_buckets)
        self.history_buckets = _validated("history_buckets", history_buckets)

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    def batch_bucket(self, n: int) -> int:
        """Smallest bucket >= n. The engine never forms a micro-batch
        larger than max_batch, so n always fits."""
        for b in self.batch_buckets:
            if n <= b:
                return b
        raise ValueError(f"micro-batch of {n} exceeds largest bucket {self.max_batch}")

    def history_bucket(self, length: int) -> int:
        """Smallest bucket >= length; histories longer than the largest
        bucket are truncated to their NEWEST max-bucket items by the
        heads (the informative tail of a user history)."""
        for b in self.history_buckets:
            if length <= b:
                return b
        return self.history_buckets[-1]

    def combos(self):
        """Every (batch, history) pair — the warmup compile grid."""
        for hb in self.history_buckets:
            for bb in self.batch_buckets:
                yield bb, hb


def default_ladder(max_batch: int = 16, max_history: int = 64) -> BucketLadder:
    """Powers-of-two ladders capped at the engine's limits."""
    batches = []
    b = 1
    while b < max_batch:
        batches.append(b)
        b *= 2
    batches.append(max_batch)
    hists = []
    h = 8
    while h < max_history:
        hists.append(h)
        h *= 2
    hists.append(max_history)
    return BucketLadder(tuple(sorted(set(batches))), tuple(sorted(set(hists))))
