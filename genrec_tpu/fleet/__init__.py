"""Fleet front: replica router, SLO-driven autoscaler, deterministic
million-user traffic harness (docs/architecture.md L7, docs/SERVING.md
"Fleet front").

One `ServingEngine` can serve all four heads with paged KV, a prefix
cache, hot swaps, an HBM ledger, and SLO-driven shedding — "millions of
users" means N of them behind a front:

- `router.FleetRouter` — the engine's `submit() -> Future` surface over
  N in-process replicas, routed by live per-head headroom; a replica's
  `OverloadError` means try-the-next, replica death means typed
  at-most-once re-submit of stranded flights.
- `autoscaler.Autoscaler` — sustained fleet-wide shed ⇒ scale-out
  (warmup = the measured AOT ladder), sustained all-replica headroom ⇒
  graceful drain scale-in, hysteresis mirroring obs/slo.py.
- `traffic` — seeded Zipfian/diurnal/burst open-loop replay, bit-
  identically reproducible so p99-under-burst and shed-rate gate in
  bench_gate; chaos hooks (kill a replica mid-burst) ride the schedule.

Layering: fleet imports serving and obs; nothing imports fleet.
"""

from genrec_tpu.fleet.autoscaler import Autoscaler, AutoscalerConfig
from genrec_tpu.fleet.router import FleetRouter, ReplicaLostError
from genrec_tpu.fleet.traffic import (
    Burst,
    ReplayReport,
    TenantTraffic,
    Trace,
    TraceConfig,
    generate_trace,
    replay,
    zipfian_repeat_user_trace,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "Burst",
    "FleetRouter",
    "ReplayReport",
    "ReplicaLostError",
    "TenantTraffic",
    "Trace",
    "TraceConfig",
    "generate_trace",
    "replay",
    "zipfian_repeat_user_trace",
]
