"""Fleet router: one `submit()` surface over N `ServingEngine` replicas.

PR 10 taught a single engine to SAY no (`OverloadError` on sustained SLO
breach); this layer is what turns that signal into capacity. The
`FleetRouter` owns N in-process replicas (each a full `ServingEngine`:
own queues, own paged pools, own SLO monitor, own AOT-compiled ladder)
and exposes the engine's exact request surface — `submit() -> Future`,
the same typed errors — so a caller cannot tell one replica from a
fleet, except that the fleet absorbs what a single replica would shed.

Routing policy — cheapest signal that tracks live load:

- Every replica's `stats()` carries a flat per-head ``headroom`` leaf
  (SLO margin minus queue pressure; serving/engine.py). The router
  refreshes a cached copy at most every ``headroom_refresh_s`` and
  ranks candidates by it, tie-broken by the router's own live in-flight
  count — no percentile math, no nested-dict walks on the submit path.
- A replica's recoverable `OverloadError` means "try the next replica":
  the router walks the ranking and only surfaces `OverloadError` to the
  caller when EVERY live replica shed (the fleet is saturated — that is
  the autoscaler's cue, counted as ``fleet_shed_rejected``).
- `DrainingError` from a replica (scale-in, signal) just removes it
  from consideration for that request.

Failure semantics — accepted work is never silently lost:

- Every accepted request is tracked as a flight (request, caller
  future, owning replica). `kill_replica` models SIGKILL-style death:
  the replica is dropped from routing, results it produces after the
  kill are DISCARDED (a dead process's responses never arrive), and
  every non-completed flight is re-submitted to a surviving replica —
  typed, AT MOST ONCE: a request that loses its replica twice fails
  with `ReplicaLostError` instead of retrying forever, and a re-submit
  that finds no capacity fails the same way. Flight-recorder events
  (`replica_dead`, `rerouted`) narrate the episode.
- Graceful removal (`remove_replica`, the autoscaler's scale-in) is the
  PR 5 drain reused verbatim: the replica stops taking new routes,
  `engine.stop()` completes every queued and in-flight request (their
  fleet futures resolve normally), then the handle is dropped.

Threading: `submit()` runs on caller threads; flight completion
callbacks run on replica batcher threads; kill/drain/scale run on
operator or autoscaler threads. One router lock guards the replica
table and flight sets — never held across an engine call or a
`Future.result`.

Replica factories should build engines with ``handle_signals=False``:
the process-level signal path belongs to whoever owns the fleet (one
`PreemptionGuard` per process), not to each replica.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional

from genrec_tpu.obs.flight_recorder import get_flight_recorder
from genrec_tpu.obs.spans import NULL_TRACER, SpanTracer, TraceContext
from genrec_tpu.serving.types import (
    DrainingError,
    OverloadError,
    Request,
    ServingError,
)


class ReplicaLostError(ServingError):
    """The replica holding this accepted request died mid-flight and the
    typed at-most-once re-submit could not complete it (no surviving
    capacity, or the retry replica died too). The request was NOT
    silently dropped — this error is the accounting."""


class _Flight:
    """One accepted request in exactly one replica."""

    __slots__ = ("req", "fut", "replica", "retried", "settled")

    def __init__(self, req: Request, fut: Future, replica: "_Replica",
                 retried: bool):
        self.req = req
        self.fut = fut
        self.replica = replica
        self.retried = retried   # already re-submitted once (at-most-once)
        self.settled = False     # result delivered OR ownership moved


class _Replica:
    __slots__ = ("replica_id", "engine", "dead", "draining", "flights",
                 "headroom", "warmup_s", "folded")

    def __init__(self, replica_id: str, engine, warmup_s: float):
        self.replica_id = replica_id
        self.engine = engine
        self.dead = False        # SIGKILL-style: results discarded
        self.draining = False    # graceful scale-in: no new routes
        self.flights: set[_Flight] = set()
        # Cached stats()["headroom"]: {} = no data yet (a fresh replica
        # is free capacity), None = the last refresh RAISED (a sick
        # replica ranks last until it answers again).
        self.headroom: Optional[dict] = {}
        self.warmup_s = warmup_s
        self.folded = False      # final counters folded into _retired


class FleetRouter:
    """Replica router + lifecycle owner. ``make_replica(replica_id)``
    returns an UN-started `ServingEngine`; the router starts it (the
    AOT warmup ladder) and times it, so scale-out cost is a measured
    quantity on every `replica_started` flight event."""

    def __init__(
        self,
        make_replica: Callable[[str], object],
        *,
        initial_replicas: int = 2,
        headroom_refresh_s: float = 0.05,
        tracer: Optional[SpanTracer] = None,
        logger: Optional[logging.Logger] = None,
    ):
        if initial_replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        self._make_replica = make_replica
        self._initial = initial_replicas
        self._refresh_s = float(headroom_refresh_s)
        self._log = logger or logging.getLogger("genrec_tpu")
        self._flight = get_flight_recorder().scoped("fleet_router")
        # Request lineage (docs/OBSERVABILITY.md "Request lineage"): the
        # router is the OUTERMOST traced component — it mints the
        # TraceContext every downstream hop attaches to. Replicas must
        # share THIS tracer instance (build engines/fronts with
        # ``tracer=router_tracer``) so span ids stay one id space.
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._lock = threading.Lock()
        self._replicas: dict[str, _Replica] = {}
        self._seq = 0
        self._draining = False
        self._started = False
        self._next_refresh = 0.0
        # Lifetime counters (stats(); `genrec_fleet_*` counters in
        # Prometheus exposition — typing pinned in obs/export.py).
        self._counters = {
            "routed": 0,
            "rerouted": 0,
            "fleet_shed_rejected": 0,
            "replica_deaths": 0,
            "replicas_added": 0,
            "replicas_drained": 0,
        }
        # Removed replicas' final COUNTER leaves, retained so the
        # fleet-aggregated sums in stats() stay monotone across
        # scale-in and replica death — obs/export.py types them as
        # Prometheus counters, and a sum over live replicas only would
        # step backwards on every removal and scrape as a counter
        # reset (spurious rate() spikes). Gauge leaves (pool occupancy,
        # prefix entries/retained_*) are NOT retained: a gone replica
        # holds nothing.
        self._retired = {
            "completed": 0,
            "recompilations": 0,
            "by_head": {},
            "prefix_cache": {},
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        if self._started:
            raise RuntimeError("fleet already started")
        self._started = True
        for _ in range(self._initial):
            self.add_replica()
        return self

    def add_replica(self) -> str:
        """Scale-out unit: build + start (AOT-warm) one replica and add
        it to the routing set. Returns its replica id. The measured
        warmup is THE scale-out cost — the autoscaler's flight events
        carry it so capacity lag is a traced number, not a guess."""
        with self._lock:
            if self._draining:
                raise DrainingError("fleet is draining; refusing scale-out")
            rid = f"r{self._seq}"
            self._seq += 1
        engine = self._make_replica(rid)
        if getattr(engine, "replica_id", None) is None:
            engine.replica_id = rid
        if self._tracer.enabled:
            # A replica added AFTER a live set_tracer swap (autoscaler
            # backfill) must join the router's tracer/id space, or its
            # requests would trace as route-span-only fragments — and a
            # factory-baked different tracer instance would collide two
            # span-id counters inside one trace. Router tracing OFF
            # leaves the factory's choice alone.
            set_t = getattr(engine, "set_tracer", None)
            if set_t is not None:
                set_t(self._tracer)
        t0 = time.monotonic()
        if not getattr(engine, "_started", False):
            engine.start()
        warmup_s = time.monotonic() - t0
        rep = _Replica(rid, engine, warmup_s)
        with self._lock:
            # Re-check under the lock: a stop() that raced the (long,
            # lock-free) warmup above has already snapshotted the
            # replica table — registering now would leak a started
            # engine no drain path will ever visit.
            aborted = self._draining
            if not aborted:
                self._replicas[rid] = rep
                self._counters["replicas_added"] += 1
                n = len(self._replicas)
        if aborted:
            try:
                engine.stop(timeout=60)
            except Exception:  # noqa: BLE001
                self._log.exception(
                    f"fleet: stopping orphaned replica {rid} failed"
                )
            raise DrainingError(
                "fleet drained during replica warmup; replica discarded"
            )
        self._flight.record(
            "replica_started", replica_id=rid,
            warmup_s=round(warmup_s, 3),
            warmup_compiles=engine.metrics.warmup_compiles,
            n_replicas=n,
        )
        self._log.info(
            f"fleet: replica {rid} up in {warmup_s:.2f}s "
            f"({engine.metrics.warmup_compiles} warmup compiles, "
            f"{n} replicas)"
        )
        return rid

    # Prefix-cache leaves that are Prometheus COUNTERS (obs/export.py);
    # the entries/retained_pages/retained_bytes leaves are gauges and
    # must NOT be retained for removed replicas.
    _PREFIX_COUNTER_LEAVES = frozenset({
        "lookups", "hits", "partial_hits", "misses", "warm_tokens",
        "insertions", "evictions", "invalidations",
    })

    def _fold_retired(self, rep: _Replica, s: dict) -> None:
        """Fold a removed replica's final counter totals into the
        retained accumulator (once per replica, under the lock)."""
        with self._lock:
            self._fold_retired_locked(rep, s)

    def _fold_retired_locked(self, rep: _Replica, s: dict) -> None:
        """Body of :meth:`_fold_retired`; caller holds ``self._lock``."""
        if rep.folded:
            return
        rep.folded = True
        ret = self._retired
        ret["completed"] += s.get("completed", 0)
        ret["recompilations"] += s.get("recompilations", 0)
        for head, n in (s.get("submitted_by_head") or {}).items():
            d = ret["by_head"].setdefault(
                head, {"submitted": 0, "overload_rejected": 0})
            d["submitted"] += n
        for head, n in (s.get("overload_by_head") or {}).items():
            d = ret["by_head"].setdefault(
                head, {"submitted": 0, "overload_rejected": 0})
            d["overload_rejected"] += n
        for head, pc in (s.get("prefix_cache") or {}).items():
            agg = ret["prefix_cache"].setdefault(head, {})
            for k, v in pc.items():
                if (k in self._PREFIX_COUNTER_LEAVES
                        and isinstance(v, (int, float))):
                    agg[k] = agg.get(k, 0) + v

    def kill_replica(self, replica_id: str) -> int:
        """SIGKILL-style death (the chaos harness's hook): the replica
        vanishes from routing, anything it produces from now on is
        discarded, and its non-completed flights are re-submitted (typed,
        at most once) to the survivors. Returns the stranded count."""
        with self._lock:
            rep = self._replicas.pop(replica_id, None)
            if rep is None or rep.dead:
                return 0
            rep.dead = True
            stranded = [fl for fl in rep.flights if not fl.settled]
            for fl in stranded:
                fl.settled = True  # the dead replica can never settle these
            rep.flights.clear()
            self._counters["replica_deaths"] += 1
            survivors = len(self._replicas)
        # Snapshot the counters it racked up BEFORE it died (deliveries
        # up to the kill) so the fleet's counter sums stay monotone;
        # anything it "completes" after this instant is discarded work
        # and deliberately uncounted.
        try:
            self._fold_retired(rep, rep.engine.stats())
        except Exception:  # noqa: BLE001 — a dead replica owes us nothing
            with self._lock:
                rep.folded = True
        self._flight.record(
            "replica_dead", replica_id=replica_id, cause="killed",
            stranded=len(stranded), n_replicas=survivors,
        )
        self._log.warning(
            f"fleet: replica {replica_id} died with {len(stranded)} "
            f"requests in flight — rerouting to {survivors} survivors"
        )
        # Reap the abandoned engine's threads off this thread; every
        # result it still produces is dropped by the dead-check in
        # _on_replica_done (a dead process's responses never arrive).
        threading.Thread(
            target=self._reap, args=(rep,), daemon=True,
            name=f"fleet-reap-{replica_id}",
        ).start()
        for fl in stranded:
            self._reroute(fl, from_replica=replica_id)
        return len(stranded)

    def _reap(self, rep: _Replica) -> None:
        try:
            rep.engine.stop(timeout=60)
        except Exception:  # noqa: BLE001 — a dead replica owes us nothing
            self._log.exception(
                f"fleet: reaping killed replica {rep.replica_id} failed"
            )

    def engine(self, replica_id: str):
        """The live replica's engine — the per-replica handle the
        rollout controller (serving/rollout.py) needs to stage candidate
        params on ONE canary and then fleet-wide (`stage_params`) and to
        read `params_step` provenance. Raises KeyError for unknown or
        dead replicas; routing/draining state is unaffected by anything
        the caller does except the engine's own staging path."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.dead:
                raise KeyError(f"no live replica {replica_id!r}")
            return rep.engine

    def remove_replica(self, replica_id: str, timeout: float = 60.0) -> dict:
        """Graceful scale-in: stop routing to the replica, drain it (the
        PR 5 path — queued + in-flight complete, their fleet futures
        resolve normally), then drop the handle. Returns the replica's
        final stats snapshot."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.dead:
                raise KeyError(f"no live replica {replica_id!r}")
            rep.draining = True
        final = rep.engine.stop(timeout)
        with self._lock:
            # Fold + pop atomically: a concurrent stats() scrape must
            # never see the replica's counters both live and retired.
            self._fold_retired_locked(rep, final)
            self._replicas.pop(replica_id, None)
            self._counters["replicas_drained"] += 1
            n = len(self._replicas)
        self._flight.record(
            "replica_drained", replica_id=replica_id,
            completed=final.get("completed"), n_replicas=n,
        )
        self._log.info(
            f"fleet: replica {replica_id} drained and removed "
            f"({final.get('completed')} lifetime requests, {n} replicas)"
        )
        return final

    def stop(self, timeout: float = 60.0) -> dict:
        """Drain the whole fleet: reject new submissions (typed), finish
        every accepted request, stop every replica. Returns the final
        aggregate stats. Idempotent."""
        with self._lock:
            already = self._draining
            self._draining = True
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.dead:
                continue  # the kill path already folded its counters
            try:
                final_r = rep.engine.stop(timeout)
            except Exception:  # noqa: BLE001 — drain the rest regardless
                self._log.exception(
                    f"fleet: stopping replica {rep.replica_id} failed"
                )
            else:
                self._fold_retired(rep, final_r)
        with self._lock:
            self._replicas.clear()
        # After the clear the aggregate reads pure retired counters, so
        # the returned final stats can never double-count a replica.
        final = self.stats()
        if not already:
            self._flight.record(
                "fleet_stopped", completed=final.get("completed"),
                replicas=len(reps),
            )
        return final

    @property
    def draining(self) -> bool:
        return self._draining

    def set_tracer(self, tracer: Optional[SpanTracer]) -> None:
        """Swap lineage tracing LIVE, fleet-wide: the router's own
        route/reroute spans and every live replica's engine/front spans
        (all sharing one tracer id space). None turns tracing off —
        the bench harness measures exactly this toggle."""
        self._tracer = tracer if tracer is not None else NULL_TRACER
        with self._lock:
            reps = [r for r in self._replicas.values() if not r.dead]
        for r in reps:
            set_t = getattr(r.engine, "set_tracer", None)
            if set_t is not None:
                set_t(tracer)

    def replica_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    # -- request path --------------------------------------------------------

    def submit(self, req: Request) -> Future:
        """The engine surface, fleet-wide: returns a Future; raises the
        typed `DrainingError` when the fleet is stopping and
        `OverloadError` only when EVERY live replica sheds."""
        if self._draining:
            raise DrainingError(
                "fleet is draining; request rejected — fail over"
            )
        fut = Future()
        tracer = self._tracer
        if req.trace is None and tracer.enabled:
            # Outermost submit: mint the request's lineage. The root
            # "request" span is recorded when the CALLER's future
            # resolves (the whole routed life, reroutes included); the
            # pre-allocated span id is the attach point every
            # downstream hop parents onto via Request.trace.
            tid = tracer.new_trace()
            root = tracer.allocate_span_id()
            req = dataclasses.replace(
                req, trace=TraceContext(tid, root, "fleet_router")
            )
            t_sub = time.monotonic()
            head = req.head

            def _record_root(f, tid=tid, root=root, t_sub=t_sub,
                             head=head):
                try:
                    outcome = "error" if f.exception() else "ok"
                except Exception:  # noqa: BLE001 — cancelled future
                    outcome = "cancelled"
                tracer.record_span(
                    "request", tid, t_sub, time.monotonic(),
                    span_id=root, head=head, origin="fleet_router",
                    component="fleet_router", outcome=outcome,
                )

            fut.add_done_callback(_record_root)
        self._traced_dispatch(req, fut, retried=False)
        return fut

    def _traced_dispatch(self, req: Request, fut: Future,
                         retried: bool) -> str:
        """_dispatch wrapped in the routing-decision span: which replica
        took the request (or that the whole fleet shed) becomes part of
        the request's own trace, not just a log line."""
        ctx = req.trace
        if ctx is None or not self._tracer.enabled:
            return self._dispatch(req, fut, retried)
        t0 = time.monotonic()
        try:
            rid = self._dispatch(req, fut, retried)
        except ServingError as e:
            self._tracer.record_span(
                "route", ctx.trace_id, t0, time.monotonic(),
                parent_id=ctx.parent_span_id, component="fleet_router",
                outcome=type(e).__name__,
            )
            raise
        self._tracer.record_span(
            "route", ctx.trace_id, t0, time.monotonic(),
            parent_id=ctx.parent_span_id, component="fleet_router",
            replica=rid, outcome="ok",
        )
        return rid

    def _ranked(self, head: str) -> list[_Replica]:
        now = time.monotonic()
        refresh = False
        with self._lock:
            if now >= self._next_refresh:
                self._next_refresh = now + self._refresh_s
                refresh = True
            reps = [r for r in self._replicas.values()
                    if not r.dead and not r.draining]
        if refresh:
            # Outside the router lock: stats() takes the engine's own
            # locks. A racing kill marks the replica dead; the stale
            # cache entry is harmless (submit() re-checks liveness).
            for r in reps:
                try:
                    r.headroom = r.engine.stats()["headroom"]
                except Exception:  # noqa: BLE001 — a sick replica ranks last
                    r.headroom = None
        with self._lock:
            return sorted(
                (r for r in reps if not r.dead and not r.draining),
                key=lambda r: (-(r.headroom.get(head, 1.0)
                                 if r.headroom is not None else -1.0),
                               len(r.flights), r.replica_id),
            )

    def _dispatch(self, req: Request, fut: Future, retried: bool) -> str:
        """Place one request on the best live replica; raises typed
        errors when nothing accepts. Returns the accepting replica id."""
        ranked = self._ranked(req.head)
        sheds = 0
        for rep in ranked:
            try:
                ef = rep.engine.submit(req)
            except OverloadError:
                sheds += 1  # this replica sheds: try the next one
                continue
            except DrainingError:
                continue    # dying replica: not a capacity signal
            # Anything else (UnknownHeadError, validation) is a caller
            # bug identical on every replica — propagate.
            flight = _Flight(req, fut, rep, retried)
            with self._lock:
                if rep.dead:
                    # Killed between submit and registration: its results
                    # are discarded, so this acceptance never counts.
                    flight.settled = True
                else:
                    rep.flights.add(flight)
                    self._counters["routed"] += 1
            if flight.settled and rep.dead:
                continue
            ef.add_done_callback(
                lambda f, fl=flight: self._on_replica_done(fl, f)
            )
            return rep.replica_id
        if ranked and sheds == len(ranked):
            with self._lock:
                self._counters["fleet_shed_rejected"] += 1
            raise OverloadError(
                f"all {len(ranked)} replicas are load-shedding for head "
                f"{req.head!r} (fleet saturated); back off and retry"
            )
        if self._draining:
            raise DrainingError("fleet is draining; request rejected")
        with self._lock:
            self._counters["fleet_shed_rejected"] += 1
        raise OverloadError(
            f"no live replica accepted head {req.head!r} "
            f"({len(ranked)} candidates); the fleet is at zero capacity"
        )

    def _on_replica_done(self, flight: _Flight, ef: Future) -> None:
        """Replica batcher thread: move the replica future's outcome to
        the caller's fleet future — unless the replica died first, in
        which case the kill path owns the flight (its 'result' is a
        message from a dead process; dropping it is the simulation's
        fidelity, and the reroute already re-placed the request)."""
        with self._lock:
            if flight.settled or flight.replica.dead:
                return
            flight.settled = True
            flight.replica.flights.discard(flight)
        exc = ef.exception()
        if flight.fut.done():  # caller cancelled: nothing to deliver
            return
        if exc is None:
            flight.fut.set_result(ef.result())
        else:
            flight.fut.set_exception(exc)

    def _reroute(self, flight: _Flight, from_replica: str) -> None:
        """Typed, at-most-once re-submit of a stranded flight. The
        flight's `Request.trace` rides the re-submit unchanged, so the
        surviving replica ADOPTS the original trace/request id —
        `Response.request_id` provenance survives the death, and the
        episode shows in the original trace as a typed ``reroute`` span
        (never a fresh orphan trace)."""
        if flight.fut.done():
            return
        ctx = flight.req.trace if self._tracer.enabled else None
        t0 = time.monotonic()

        def _span(outcome: str, to: Optional[str] = None) -> None:
            if ctx is None:
                return
            self._tracer.record_span(
                "reroute", ctx.trace_id, t0, time.monotonic(),
                parent_id=ctx.parent_span_id, component="fleet_router",
                rerouted_from=from_replica, replica_to=to,
                outcome=outcome,
            )

        if flight.retried:
            _span("retry_exhausted")
            flight.fut.set_exception(ReplicaLostError(
                f"request lost replica {from_replica} after already being "
                "re-routed once (at-most-once retry exhausted)"
            ))
            return
        try:
            to = self._dispatch(flight.req, flight.fut, retried=True)
        except ServingError as e:
            _span("no_capacity")
            flight.fut.set_exception(ReplicaLostError(
                f"replica {from_replica} died mid-flight and the re-submit "
                f"found no capacity: {e}"
            ))
            return
        _span("ok", to)
        with self._lock:
            self._counters["rerouted"] += 1
        self._flight.record(
            "rerouted", head=flight.req.head,
            replica_from=from_replica, replica_to=to,
            trace_id=flight.req.trace.trace_id
            if flight.req.trace is not None else None,
        )

    # -- autoscaler / observability surface ----------------------------------

    def scale_signal(self) -> dict:
        """Per-replica scalar load state for the autoscaler: min-over-
        heads headroom and whether the replica currently sheds."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if not r.dead and not r.draining]
        per = {}
        for rep in reps:
            try:
                s = rep.engine.stats()
            except Exception:  # noqa: BLE001 — a sick replica reads as full
                per[rep.replica_id] = {"headroom": -1.0, "shedding": True}
                continue
            room = s.get("headroom") or {}
            hr = min(room.values()) if room else 1.0
            shedding = bool((s.get("slo") or {}).get("shedding")) or hr <= 0.0
            per[rep.replica_id] = {
                "headroom": round(hr, 4), "shedding": shedding,
            }
        return {"replicas": per, "alive": len(per)}

    def stats(self) -> dict:
        """Fleet-aggregated snapshot: router counters + per-head sums of
        every live replica's submit/overload/prefix-cache counters +
        per-replica gauges. `write_prometheus(path, router.stats(),
        namespace="genrec_fleet")` exposes it — counter/gauge typing is
        pinned by the leaf names (obs/export.py)."""
        with self._lock:
            reps = list(self._replicas.values())
            counters = dict(self._counters)
            total = self._seq
            # Removed replicas' retained counter totals seed the sums,
            # keeping every counter-typed leaf monotone across
            # scale-in/death (a live-only sum would scrape as a
            # counter reset).
            by_head = {h: dict(d)
                       for h, d in self._retired["by_head"].items()}
            prefix = {h: dict(d)
                      for h, d in self._retired["prefix_cache"].items()}
            recompilations = self._retired["recompilations"]
            completed = self._retired["completed"]
        replicas: dict[str, dict] = {}
        for rep in reps:
            if rep.dead or rep.folded:
                continue
            try:
                s = rep.engine.stats()
            except Exception:  # noqa: BLE001 — a sick replica drops out
                continue
            recompilations += s.get("recompilations", 0)
            completed += s.get("completed", 0)
            pool = s.get("kv_pool") or {}
            replicas[rep.replica_id] = {
                "submitted": s.get("submitted", 0),
                "completed": s.get("completed", 0),
                "overload_rejected": s.get("overload_rejected", 0),
                "recompilations": s.get("recompilations", 0),
                "queue_depth": sum((s.get("queue_depth") or {}).values()),
                # Paged-pool occupancy summed over heads: "all pages
                # released after drain" is checked FLEET-wide
                # (scripts/check_fleet.py) off these two gauges.
                "pages_in_use": sum(g.get("pages_in_use", 0)
                                    for g in pool.values()),
                "slots_active": sum(g.get("slots_active", 0)
                                    for g in pool.values()),
                "headroom": dict(s.get("headroom") or {}),
                "draining": bool(s.get("draining")),
                "warmup_s": round(rep.warmup_s, 3),
            }
            for head, n in (s.get("submitted_by_head") or {}).items():
                by_head.setdefault(head, {"submitted": 0,
                                          "overload_rejected": 0})
                by_head[head]["submitted"] += n
            for head, n in (s.get("overload_by_head") or {}).items():
                by_head.setdefault(head, {"submitted": 0,
                                          "overload_rejected": 0})
                by_head[head]["overload_rejected"] += n
            for head, pc in (s.get("prefix_cache") or {}).items():
                agg = prefix.setdefault(head, {})
                for k, v in pc.items():
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
        return {
            **counters,
            "replicas_alive": len(replicas),
            "replicas_total": total,
            "completed": completed,
            "recompilations": recompilations,
            "by_head": by_head,
            "prefix_cache": prefix,
            "replicas": replicas,
            # Fleet-level tracer self-metering (lineage liveness).
            "tracing": self._tracer.stats(),
        }
