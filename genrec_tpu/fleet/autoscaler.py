"""SLO-driven autoscaler: sustained fleet-wide breach ⇒ scale-out,
sustained all-replica headroom ⇒ scale-in, with hysteresis.

The router turns one replica's shed into "try the next replica"; the
autoscaler turns the FLEET's shed into capacity. Its inputs are the
cheap scalars the fleet already publishes (`FleetRouter.scale_signal()`:
per-replica min-headroom + shedding flag, derived from each replica's
`SLOMonitor` margins), its outputs are the router's two lifecycle verbs:

- **scale-out** — when EVERY live replica is shedding (the same
  condition under which `submit()` surfaces the fleet-level
  `OverloadError`) and that has held for ``scale_out_after_s``, add one
  replica. Warmup is the existing AOT compile ladder, so the scale-out
  cost is a measured number on the `replica_started` /`scale_out`
  flight events — capacity lag is traced, not guessed.
- **scale-in** — when every replica's headroom has stayed above
  ``scale_in_headroom`` for ``scale_in_after_s`` and the fleet is above
  ``min_replicas``, gracefully remove the FREEST replica
  (`remove_replica`: the PR 5 drain — queued + in-flight complete
  before teardown, nothing is dropped to save power).

Hysteresis mirrors `obs/slo.py`: a condition must HOLD for its window
(a blip resets the clock), and every action starts a ``cooldown_s``
during which neither clock accumulates — a new replica needs its warmup
plus a window of traffic before the fleet's state means anything.

`tick(now=...)` is the whole state machine (fake-clock testable, like
SLOMonitor); `start()` just runs it on a poll thread.

Layering: fleet imports serving/obs only (docs/architecture.md L7).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

from genrec_tpu.obs.flight_recorder import get_flight_recorder


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Bounds + hysteresis windows. Defaults suit the in-process bench
    fleets; production fleets stretch the windows to real warmup cost."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_out_after_s: float = 1.0    # sustained all-replica shed
    scale_in_after_s: float = 10.0    # sustained all-replica headroom
    scale_in_headroom: float = 0.5    # per-replica min-headroom floor
    cooldown_s: float = 2.0           # after any action, clocks reset
    poll_secs: float = 0.25

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got {self}"
            )
        if min(self.scale_out_after_s, self.scale_in_after_s,
               self.cooldown_s) < 0 or self.poll_secs <= 0:
            raise ValueError(f"invalid autoscaler windows in {self}")


class Autoscaler:
    """Hysteresis state machine over `router.scale_signal()`."""

    def __init__(self, router, config: Optional[AutoscalerConfig] = None,
                 logger: Optional[logging.Logger] = None):
        self.router = router
        self.config = config or AutoscalerConfig()
        self._log = logger or logging.getLogger("genrec_tpu")
        self._flight = get_flight_recorder().scoped("autoscaler")
        self._lock = threading.Lock()
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._cooldown_until = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scale_outs = 0
        self.scale_ins = 0
        self.last_warmup_s: Optional[float] = None

    # -- the state machine ---------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation; returns "scale_out" / "scale_in" when an
        action fired, else None. Pass ``now`` for fake-clock tests."""
        fake_now = None if now is None else float(now)
        now = time.monotonic() if now is None else float(now)
        sig = self.router.scale_signal()
        replicas = sig["replicas"]
        alive = sig["alive"]
        cfg = self.config
        with self._lock:
            if now < self._cooldown_until:
                # Cooldown: a freshly warmed replica (or a just-drained
                # fleet) needs a window of traffic before the signal
                # means anything; neither clock accumulates.
                self._breach_since = None
                self._idle_since = None
                return None
            # Fleet-wide breach: every live replica sheds (the condition
            # under which the router surfaces OverloadError), or deaths
            # dropped the fleet below its floor — backfill after a kill
            # rides the same hysteresis clock.
            backfill = alive < cfg.min_replicas
            breaching = backfill or (
                alive > 0 and all(r["shedding"] for r in replicas.values())
            )
            idle = alive > 0 and all(
                not r["shedding"] and r["headroom"] >= cfg.scale_in_headroom
                for r in replicas.values()
            )
            if breaching and alive < cfg.max_replicas:
                self._idle_since = None
                if self._breach_since is None:
                    self._breach_since = now
                if now - self._breach_since < cfg.scale_out_after_s:
                    return None
                self._breach_since = None
                action = "scale_out"
            elif idle and alive > cfg.min_replicas:
                self._breach_since = None
                if self._idle_since is None:
                    self._idle_since = now
                if now - self._idle_since < cfg.scale_in_after_s:
                    return None
                self._idle_since = None
                action = "scale_in"
            else:
                # Neither condition holds (or bounds bind): both clocks
                # reset — sustained means CONTINUOUSLY, as in obs/slo.py.
                self._breach_since = None
                self._idle_since = None
                return None
        if action == "scale_out":
            # A backfill (deaths took the pool below its floor — a lost
            # decode host, a crashed replica) is operationally distinct
            # from capacity scale-out: the post-mortem should show WHY
            # capacity was added, and a standby promotion that replaces
            # a dead remote host reads differently from chasing load.
            return self._scale_out(
                alive, fake_now,
                reason=("dead_replica_backfill" if backfill
                        else "sustained fleet-wide shed"),
            )
        return self._scale_in(replicas, alive, fake_now)

    def _start_cooldown(self, fake_now: Optional[float]) -> None:
        """Cooldown starts when the action COMPLETES — add_replica
        blocks through the whole AOT warmup, and a cooldown clocked
        from the decision instant would already be spent by the time a
        slow-warming replica joins, letting back-to-back scale-outs
        defeat the settling window the docstring promises. On the fake
        clock the action is instantaneous, so the passed ``now`` is the
        completion time."""
        end = time.monotonic() if fake_now is None else fake_now
        with self._lock:
            self._cooldown_until = end + self.config.cooldown_s

    def _scale_out(self, alive: int,
                   fake_now: Optional[float] = None,
                   reason: str = "sustained fleet-wide shed",
                   ) -> Optional[str]:
        t0 = time.monotonic()
        try:
            rid = self.router.add_replica()
        except Exception:  # noqa: BLE001 — scaling must not kill the loop
            self._log.exception("fleet: scale-out failed")
            self._start_cooldown(fake_now)  # throttle retry after failure
            return None
        warmup_s = time.monotonic() - t0
        self._start_cooldown(fake_now)
        with self._lock:
            self.scale_outs += 1
            self.last_warmup_s = round(warmup_s, 3)
        self._flight.record(
            "scale_out", replica_id=rid, warmup_s=round(warmup_s, 3),
            n_replicas=alive + 1, reason=reason,
        )
        self._log.warning(
            f"fleet: scale-OUT -> {rid} ({reason}; warmup "
            f"{warmup_s:.2f}s, now {alive + 1} replicas)"
        )
        return "scale_out"

    def _scale_in(self, replicas: dict, alive: int,
                  fake_now: Optional[float] = None) -> Optional[str]:
        # Drain the FREEST replica: least in-flight disruption, and the
        # survivors keep the most loaded working sets warm.
        rid = max(replicas, key=lambda r: replicas[r]["headroom"])
        try:
            self.router.remove_replica(rid)
        except Exception:  # noqa: BLE001
            self._log.exception(f"fleet: scale-in of {rid} failed")
            self._start_cooldown(fake_now)  # throttle retry after failure
            return None
        with self._lock:
            self.scale_ins += 1
        self._start_cooldown(fake_now)
        self._flight.record(
            "scale_in", replica_id=rid, n_replicas=alive - 1,
            reason="sustained all-replica headroom",
        )
        self._log.info(
            f"fleet: scale-IN {rid} drained and removed "
            f"(now {alive - 1} replicas)"
        )
        return "scale_in"

    # -- poll thread ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_secs):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                self._log.exception("fleet: autoscaler tick failed")

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "last_warmup_s": self.last_warmup_s,
                "cooling_down": time.monotonic() < self._cooldown_until,
            }
