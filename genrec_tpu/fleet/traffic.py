"""Deterministic million-user traffic harness: seeded open-loop replay.

The fleet front (router + autoscaler) is only testable against traffic
that looks like production — Zipfian user popularity over millions of
distinct user ids, session arrivals whose rate swings diurnally and
spikes in bursts — and only GATEABLE when that traffic replays
bit-identically: the same seed must produce the same arrival schedule
down to the last float, so `bench_gate` can pin p99-under-burst and
shed-rate as regression metrics instead of anecdotes.

Three pieces:

- `TraceConfig` + `generate_trace` — the schedule generator. User
  popularity is Zipfian over ranks (p ∝ 1/rank^zipf_a; the probability
  vector is O(n_users) float64, so tens of millions of DISTINCT ids are
  one ~100MB host array — the "millions of users" scale is the id
  space, while per-user state materializes lazily for the users a trace
  actually visits). Arrival times are an inhomogeneous Poisson process
  (Lewis thinning against the peak rate) whose rate is the base QPS
  modulated by a sinusoidal diurnal factor and piecewise-constant burst
  multipliers. Everything is drawn from ONE seeded np.random.Generator
  in a fixed order: same config ⇒ bit-identical `Trace`.
- `replay` — the open-loop driver: submits each arrival at its scheduled
  (time-scaled) offset WITHOUT waiting for responses (open loop: an
  overloaded server does not slow the offered load down — the property
  closed-loop drivers silently lose), counts typed sheds
  (`OverloadError`) and drains per arrival, then gathers completions
  into a `ReplayReport` with p99-under-burst and shed-rate. Chaos
  belongs in the harness: `chaos=[(t, fn), ...]` fires each hook once
  when trace time passes `t` — killing a replica mid-burst is
  `(burst_start + eps, lambda: router.kill_replica("r0"))`.
- `zipfian_repeat_user_trace` — PR 11's repeat-user trace (moved here
  from bench.py, which now imports it): the closed-form repeat/refresh
  workload the prefix-cache bench drives. `generate_trace` generalizes
  it with real arrival TIMES; this stays for the benches that only need
  the request sequence.

Layering: fleet sits above serving (docs/architecture.md L7) — this
module imports serving's `Request` type only.
"""

from __future__ import annotations

import dataclasses
import math
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Optional, Sequence

import numpy as np

from genrec_tpu.serving.types import (
    DrainingError,
    OverloadError,
    Request,
)


@dataclasses.dataclass(frozen=True)
class Burst:
    """A load spike: ``rate_mult`` x the diurnal rate over
    [t_start, t_start + duration_s)."""

    t_start: float
    duration_s: float
    rate_mult: float

    def covers(self, t: float) -> bool:
        return self.t_start <= t < self.t_start + self.duration_s


@dataclasses.dataclass(frozen=True)
class TenantTraffic:
    """One tenant's slice of a mixed trace (``TraceConfig.tenants``).

    ``rate_share`` is the tenant's relative weight in the arrival mix
    (normalized across tenants per arrival); ``burst_mult`` multiplies
    that weight inside burst windows — the hot-tenant knob: an
    aggressor with ``burst_mult=6`` surges to ~6x its share mid-burst
    while the TOTAL offered rate still follows the config's burst
    envelope, which is exactly the co-tenancy victim scenario the
    isolation bench gates. ``n_users`` bounds the tenant's OWN user-id
    space (defaults to the config's); tenant ids never collide —
    tenant ``i``'s users live at ``i * n_users + local``.
    """

    name: str
    head: str
    rate_share: float = 1.0
    n_users: Optional[int] = None
    burst_mult: float = 1.0

    def __post_init__(self):
        if self.rate_share <= 0 or self.burst_mult <= 0:
            raise ValueError(f"invalid tenant traffic {self}")


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Shape of one deterministic traffic trace.

    ``n_users`` is the DISTINCT-id space (millions-capable; the Zipf
    probability vector is the only O(n_users) cost). ``item_lo`` lets
    retrieval-head traces use 1-based vocab ids (0 = pad). The diurnal
    factor is ``1 + diurnal_amplitude * sin(2π t / diurnal_period_s)``
    — one synthetic "day" per period, compressed so tests and benches
    see a full cycle in seconds. ``tenants`` turns the trace into a
    multi-tenant mix: each arrival is assigned a tenant (and that
    tenant's head + user space) from a SECOND seeded stream, so adding
    tenants never perturbs the base schedule's draw order — a
    tenant-free config stays bit-identical to what it generated before
    tenants existed.
    """

    n_requests: int = 256
    n_users: int = 1_000_000
    max_items: int = 20
    corpus_size: int = 100
    head: str = "tiger"
    seed: int = 0
    zipf_a: float = 1.5
    p_new_item: float = 0.25
    base_rate_qps: float = 32.0
    diurnal_period_s: float = 60.0
    diurnal_amplitude: float = 0.5
    bursts: tuple[Burst, ...] = ()
    item_lo: int = 0  # retrieval heads: 1 (0 is the pad id)
    tenants: tuple[TenantTraffic, ...] = ()

    def __post_init__(self):
        if not 0 <= self.diurnal_amplitude < 1:
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        if self.base_rate_qps <= 0 or self.n_requests <= 0:
            raise ValueError(f"invalid trace config {self}")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names in {names}")

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (QPS) at trace time ``t``."""
        rate = self.base_rate_qps * (
            1.0 + self.diurnal_amplitude
            * math.sin(2.0 * math.pi * t / self.diurnal_period_s)
        )
        for b in self.bursts:
            if b.covers(t):
                rate *= b.rate_mult
        return rate

    @property
    def peak_rate(self) -> float:
        """Supremum of `rate_at` over all t — the Lewis-thinning
        envelope. Burst windows may OVERLAP (`rate_at` multiplies every
        covering burst), so the envelope is the max multiplier PRODUCT
        over the piecewise-constant segments the burst boundaries
        induce — a single largest-multiplier bound would let the
        acceptance ratio exceed 1 inside an overlap and silently cap
        the realized rate there."""
        peak = self.base_rate_qps * (1.0 + self.diurnal_amplitude)
        bounds = sorted({b.t_start for b in self.bursts}
                        | {b.t_start + b.duration_s for b in self.bursts})
        best = 1.0
        for lo, hi in zip(bounds, bounds[1:]):
            mid = (lo + hi) / 2.0
            prod = 1.0
            for b in self.bursts:
                if b.covers(mid):
                    prod *= b.rate_mult
            best = max(best, prod)
        return peak * best

    def in_burst(self, t: float) -> bool:
        return any(b.covers(t) for b in self.bursts)


@dataclasses.dataclass(frozen=True)
class Arrival:
    t: float          # trace-time offset (s) from replay start
    user_id: int
    history: np.ndarray
    in_burst: bool
    tenant: Optional[str] = None  # multi-tenant mixes only
    head: Optional[str] = None    # tenant's head; None -> config.head


@dataclasses.dataclass(frozen=True)
class Trace:
    config: TraceConfig
    arrivals: tuple[Arrival, ...]

    def __len__(self) -> int:
        return len(self.arrivals)

    def schedule(self) -> np.ndarray:
        """(n,) float64 arrival offsets — the bit-identity surface the
        determinism test compares."""
        return np.array([a.t for a in self.arrivals], np.float64)

    def requests(self) -> list[Request]:
        cfg = self.config
        return [Request(head=a.head or cfg.head, history=a.history,
                        user_id=a.user_id)
                for a in self.arrivals]


def _zipf_probs(n_users: int, zipf_a: float) -> np.ndarray:
    ranks = np.arange(1, n_users + 1, dtype=np.float64)
    p = ranks ** -zipf_a
    p /= p.sum()
    return p


#: Salt for the tenant-assignment stream: a SECOND generator seeded
#: from (cfg.seed, salt) so tenant draws never touch the base stream's
#: order — tenant-free configs stay bit-identical across this feature.
_TENANT_STREAM_SALT = 0x7E9A97


def _assign_tenant(cfg: TraceConfig, trng, burst: bool):
    """One tenant pick: a single uniform draw against the (burst-
    adjusted, normalized) rate shares — exactly one draw per arrival,
    so the tenant stream's order is as pinned as the base stream's."""
    weights = [t.rate_share * (t.burst_mult if burst else 1.0)
               for t in cfg.tenants]
    total = sum(weights)
    draw = trng.random() * total
    acc = 0.0
    for idx, w in enumerate(weights):
        acc += w
        if draw < acc:
            return idx
    return len(weights) - 1  # float round-off on the last edge


def generate_trace(cfg: TraceConfig) -> Trace:
    """Materialize one deterministic trace: same cfg ⇒ bit-identical
    arrival times, user ids, and histories (pinned by
    tests/test_fleet.py). All randomness flows through ONE seeded
    generator in a fixed draw order — keep it that way when editing."""
    rng = np.random.default_rng(cfg.seed)
    # 1) Arrival times: Lewis thinning against the peak rate. Candidate
    # inter-arrivals are drawn at peak and accepted w.p. rate(t)/peak —
    # an exact inhomogeneous Poisson sampler, and deterministic here
    # because every candidate consumes exactly two draws.
    peak = cfg.peak_rate
    times = []
    t = 0.0
    while len(times) < cfg.n_requests:
        t += rng.exponential(1.0 / peak)
        if rng.random() <= cfg.rate_at(t) / peak:
            times.append(t)
    # 2) Users: one vectorized Zipfian draw over the full id space.
    users = rng.choice(cfg.n_users, size=cfg.n_requests,
                       p=_zipf_probs(cfg.n_users, cfg.zipf_a))
    # 3) Tenant assignment (multi-tenant mixes): a SECOND seeded stream
    # so the base stream's draw order above is untouched — configs with
    # tenants=() generate bit-identically to pre-tenant versions.
    trng = (np.random.default_rng(
        np.random.SeedSequence([cfg.seed, _TENANT_STREAM_SALT]))
        if cfg.tenants else None)
    # 4) Histories: per-user session state, created lazily on first
    # visit (ids drawn per arrival in order, so the dict never holds
    # more than the VISITED users — the id space can be millions wide).
    # In tenant mixes the session key is the NAMESPACED id (tenant i's
    # users live at i * n_users + local), so user spaces never bleed.
    histories: dict[int, list] = {}
    arrivals = []
    for t, user in zip(times, users):
        user = int(user)
        burst = cfg.in_burst(float(t))
        tenant = head = None
        if trng is not None:
            idx = _assign_tenant(cfg, trng, burst)
            ten = cfg.tenants[idx]
            tenant, head = ten.name, ten.head
            user = idx * cfg.n_users + user % (ten.n_users or cfg.n_users)
        h = histories.get(user)
        if h is None:
            n0 = int(rng.integers(3, cfg.max_items + 1))
            h = list(rng.integers(cfg.item_lo, cfg.corpus_size, n0))
        elif rng.random() < cfg.p_new_item:
            h = (h + [int(rng.integers(cfg.item_lo, cfg.corpus_size))]
                 )[-cfg.max_items:]
        histories[user] = h
        arrivals.append(Arrival(
            t=float(t), user_id=user,
            history=np.asarray(h, np.int64),
            in_burst=burst,
            tenant=tenant, head=head,
        ))
    return Trace(config=cfg, arrivals=tuple(arrivals))


@dataclasses.dataclass
class ReplayReport:
    """Outcome of one open-loop replay. ``lost`` is the invariant the
    kill-chaos tests pin at zero: every arrival is accounted for as
    completed, typed-shed, typed-drain-rejected, or failed-with-error —
    a future that silently never resolved counts as lost."""

    submitted: int = 0
    completed: int = 0
    shed: int = 0
    rejected: int = 0   # typed DrainingError at submit
    failed: int = 0     # future resolved with a non-typed error
    lost: int = 0       # future never resolved inside the gather timeout
    wall_s: float = 0.0
    offered_qps: float = 0.0
    p50_ms: Optional[float] = None
    p99_ms: Optional[float] = None
    burst_submitted: int = 0
    burst_shed: int = 0
    p99_under_burst_ms: Optional[float] = None
    late_submits: int = 0  # arrivals dispatched >1 tick behind schedule
    #: Multi-tenant mixes: {tenant: {submitted, completed, shed,
    #: shed_rate, p50_ms, p99_ms, burst_submitted, burst_shed}} — the
    #: victim-vs-aggressor split the isolation bench gates.
    tenants: dict = dataclasses.field(default_factory=dict)

    @property
    def shed_rate(self) -> float:
        return self.shed / self.submitted if self.submitted else 0.0

    @property
    def burst_shed_rate(self) -> float:
        return (self.burst_shed / self.burst_submitted
                if self.burst_submitted else 0.0)

    def summary(self) -> dict:
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "shed": self.shed,
            "rejected": self.rejected,
            "failed": self.failed,
            "lost": self.lost,
            "shed_rate": round(self.shed_rate, 4),
            "wall_s": round(self.wall_s, 2),
            "offered_qps": round(self.offered_qps, 2),
            "p50_ms": self.p50_ms,
            "p99_ms": self.p99_ms,
            "burst_submitted": self.burst_submitted,
            "burst_shed_rate": round(self.burst_shed_rate, 4),
            "p99_under_burst_ms": self.p99_under_burst_ms,
            "late_submits": self.late_submits,
            **({"tenants": self.tenants} if self.tenants else {}),
        }


def _pct(vals: Sequence[float], q: float) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    return round(vals[min(len(vals) - 1, int(q * len(vals)))] * 1e3, 3)


def replay(
    trace: Trace,
    submit: Callable[[Request], object],
    *,
    time_scale: float = 1.0,
    chaos: Sequence[tuple[float, Callable[[], None]]] = (),
    gather_timeout_s: float = 120.0,
) -> ReplayReport:
    """Drive one trace open-loop through ``submit`` (a FleetRouter's or
    a bare engine's — anything returning a Future and raising the typed
    serving errors). Arrival ``t`` maps to wall offset ``t *
    time_scale`` (compress a 60s synthetic day into 6s with 0.1).
    ``chaos`` hooks fire once each when trace time passes their ``t`` —
    BEFORE the next submit, so "kill a replica mid-burst" lands between
    two scheduled arrivals, exactly like a preemption would."""
    pending: list[tuple[Arrival, object]] = []
    report = ReplayReport()
    per_tenant: dict[str, dict] = {}
    tenant_lat: dict[str, list] = {}

    def _tstats(name: str) -> dict:
        st = per_tenant.get(name)
        if st is None:
            st = per_tenant[name] = {
                "submitted": 0, "completed": 0, "shed": 0,
                "burst_submitted": 0, "burst_shed": 0,
            }
            tenant_lat[name] = []
        return st

    hooks = sorted(chaos, key=lambda c: c[0])
    hook_i = 0
    t0 = time.monotonic()
    for arr in trace.arrivals:
        target = t0 + arr.t * time_scale
        while hook_i < len(hooks) and hooks[hook_i][0] <= arr.t:
            hooks[hook_i][1]()
            hook_i += 1
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        elif -delay > max(0.05, 0.05 * time_scale):
            report.late_submits += 1  # host fell behind the schedule
        report.submitted += 1
        if arr.in_burst:
            report.burst_submitted += 1
        tstats = _tstats(arr.tenant) if arr.tenant is not None else None
        if tstats is not None:
            tstats["submitted"] += 1
            if arr.in_burst:
                tstats["burst_submitted"] += 1
        req = Request(head=arr.head or trace.config.head,
                      history=arr.history, user_id=arr.user_id)
        try:
            fut = submit(req)
        except OverloadError:
            report.shed += 1
            if arr.in_burst:
                report.burst_shed += 1
            if tstats is not None:
                tstats["shed"] += 1
                if arr.in_burst:
                    tstats["burst_shed"] += 1
            continue
        except DrainingError:
            report.rejected += 1
            continue
        pending.append((arr, fut))
    for t_hook, fn in hooks[hook_i:]:  # hooks past the last arrival
        fn()
    lat: list[float] = []
    burst_lat: list[float] = []
    deadline = time.monotonic() + gather_timeout_s
    for arr, fut in pending:
        try:
            resp = fut.result(max(deadline - time.monotonic(), 0.001))
        except (_FutureTimeout, TimeoutError):
            report.lost += 1
            continue
        except Exception:  # noqa: BLE001 — typed per-future failure
            report.failed += 1
            continue
        report.completed += 1
        lat.append(resp.total_s)
        if arr.in_burst:
            burst_lat.append(resp.total_s)
        if arr.tenant is not None:
            _tstats(arr.tenant)["completed"] += 1
            tenant_lat[arr.tenant].append(resp.total_s)
    report.wall_s = time.monotonic() - t0
    report.offered_qps = report.submitted / report.wall_s \
        if report.wall_s > 0 else 0.0
    report.p50_ms = _pct(lat, 0.50)
    report.p99_ms = _pct(lat, 0.99)
    report.p99_under_burst_ms = _pct(burst_lat, 0.99)
    for name, st in per_tenant.items():
        st["shed_rate"] = round(st["shed"] / st["submitted"], 4) \
            if st["submitted"] else 0.0
        st["p50_ms"] = _pct(tenant_lat[name], 0.50)
        st["p99_ms"] = _pct(tenant_lat[name], 0.99)
    report.tenants = per_tenant
    return report


def zipfian_repeat_user_trace(n_requests: int, n_users: int, max_items: int,
                              corpus_size: int, rng, zipf_a: float = 1.5,
                              p_new_item: float = 0.25):
    """Deterministic repeat-user request trace (the prefix-cache bench's
    workload; PR 11, moved here from bench.py).

    User popularity is Zipfian over ranks (p ∝ 1/rank^zipf_a): a few
    heavy users dominate arrivals — recommendation traffic's shape, and
    the prefix cache's best case. Each arrival either REPEATS the user's
    previous request verbatim (a refresh / next-page fetch: warm
    full-history hit) or first appends one new interaction
    (history grew: cold, re-retained). Histories cap at ``max_items`` by
    sliding (oldest item drops), matching the serving bucket clip.

    Returns a list of (user_id, history ndarray) pairs, fully
    materialized up front so driver threads never touch the rng
    (np.random.Generator is not thread-safe)."""
    p = _zipf_probs(n_users, zipf_a)
    histories: dict = {}
    trace = []
    for _ in range(n_requests):
        user = int(rng.choice(n_users, p=p))
        h = histories.get(user)
        if h is None:
            h = list(rng.integers(0, corpus_size,
                                  int(rng.integers(3, max_items + 1))))
        elif rng.random() < p_new_item:
            h = (h + [int(rng.integers(0, corpus_size))])[-max_items:]
        histories[user] = h
        trace.append((user, np.asarray(h, np.int64)))
    return trace
