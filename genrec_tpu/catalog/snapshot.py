"""CatalogSnapshot: the item corpus as a versioned, swappable artifact.

One snapshot bundles everything the serving layer derives from the item
corpus:

- ``item_sem_ids`` (N, D) — the sem-id tuple per corpus item (the trie's
  source of truth and the beam -> item-id lookup);
- ``item_vecs`` (N, d) optional — COBRA's dense item-tower embeddings,
  precomputed by the catalog pipeline so a params-only hot reload does
  NOT re-encode the whole corpus (see CobraGenerativeHead.on_params);
- ``item_text_tokens`` (N, L) optional — the tokenized item text, for
  heads that encode the tower themselves on a catalog change;
- ``version`` — a CONTENT hash over all of the above: two snapshots with
  the same items are the same version, and a corrupted file can never
  impersonate a valid one (load() recomputes and compares);
- ``capacity`` — the TensorTrie capacity rung the snapshot pads to.
  Same-rung snapshots share executables; a rung change is the only
  recompile (done AOT by the serving staging path, never on the hot
  path).

On-disk format: one ``catalog-<version>.npz`` written ATOMICALLY
(tmp file in the target directory + ``os.replace``), so a watcher can
never observe a half-written snapshot under the final name. ``load``
verifies the content hash and raises ``CatalogIntegrityError`` on any
mismatch — the serving watcher quarantines such files and keeps serving
the previous catalog (the same contract as the checkpoint integrity
ladder).
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Optional

import numpy as np

from genrec_tpu.catalog.tensor_trie import TensorTrie

#: On-disk snapshot filename prefix/suffix.
FILE_PREFIX = "catalog-"
FILE_SUFFIX = ".npz"


class CatalogIntegrityError(RuntimeError):
    """A snapshot file failed to load or its content hash does not match
    its recorded version — the file is garbled or tampered."""


def _content_version(item_sem_ids: np.ndarray, codebook_size: int,
                     item_vecs, item_text_tokens) -> str:
    h = hashlib.sha256()
    h.update(str(int(codebook_size)).encode())
    h.update(np.ascontiguousarray(item_sem_ids).tobytes())
    for arr in (item_vecs, item_text_tokens):
        h.update(b"|")
        if arr is not None:
            h.update(str(arr.dtype).encode() + str(arr.shape).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


class CatalogSnapshot:
    """Immutable corpus artifact. Build with :meth:`build`, persist with
    :meth:`save`, restore with :meth:`load`."""

    def __init__(self, item_sem_ids: np.ndarray, codebook_size: int,
                 item_vecs: Optional[np.ndarray] = None,
                 item_text_tokens: Optional[np.ndarray] = None,
                 capacity: int = 0, version: str = ""):
        self.item_sem_ids = np.asarray(item_sem_ids, np.int64)
        self.codebook_size = int(codebook_size)
        self.item_vecs = None if item_vecs is None else np.asarray(item_vecs)
        self.item_text_tokens = (
            None if item_text_tokens is None else np.asarray(item_text_tokens)
        )
        self.capacity = int(capacity)
        self.version = version
        self._trie: Optional[TensorTrie] = None
        self._device_trie: Optional[TensorTrie] = None
        self._item_index: Optional[dict] = None
        self._quantized_vecs = None

    # -- construction --------------------------------------------------------

    @classmethod
    def build(cls, item_sem_ids: np.ndarray, codebook_size: int,
              item_vecs: Optional[np.ndarray] = None,
              item_text_tokens: Optional[np.ndarray] = None,
              capacity: Optional[int] = None) -> "CatalogSnapshot":
        """Version-stamp a corpus and pick (or pin) its capacity rung.

        ``capacity`` overrides the ladder — tests use it to force two
        snapshots onto the same (or different) rungs deliberately.
        """
        item_sem_ids = np.asarray(item_sem_ids, np.int64)
        snap = cls(item_sem_ids, codebook_size, item_vecs, item_text_tokens)
        # Build once eagerly: validates codes and sizes the rung.
        trie = TensorTrie.build(item_sem_ids, codebook_size, capacity=capacity)
        snap.capacity = trie.capacity
        snap._trie = trie
        snap.version = _content_version(
            item_sem_ids, codebook_size, snap.item_vecs, snap.item_text_tokens
        )
        return snap

    @property
    def n_items(self) -> int:
        return int(self.item_sem_ids.shape[0])

    @property
    def depth(self) -> int:
        return int(self.item_sem_ids.shape[1])

    def trie(self) -> TensorTrie:
        """The snapshot's TensorTrie at its capacity rung (cached)."""
        if self._trie is None:
            self._trie = TensorTrie.build(
                self.item_sem_ids, self.codebook_size, capacity=self.capacity
            )
        return self._trie

    def device_trie(self) -> TensorTrie:
        """The trie with its tensors on device, cached — so the serving
        swap path uploads ONCE (on the staging thread) and the batcher's
        set_catalog is a pure pointer read."""
        if self._device_trie is None:
            self._device_trie = self.trie().device()
        return self._device_trie

    def quantized_item_vecs(self):
        """``item_vecs`` as an int8 per-row-quantized ``QuantizedTable``
        (cached) — the compact scoring operand for quantized retrieval
        towers. Built ONCE per catalog version (snapshots are immutable,
        so the cache can never serve a stale quantization), on the
        staging thread like the device trie, never on the batcher.
        Raises if the snapshot carries no dense item vectors."""
        if self.item_vecs is None:
            raise ValueError(
                f"catalog {self.version or '<unversioned>'} has no "
                "item_vecs to quantize"
            )
        if self._quantized_vecs is None:
            from genrec_tpu.ops.quant import QuantizedTable

            self._quantized_vecs = QuantizedTable.from_array(
                np.asarray(self.item_vecs, np.float32)
            )
        return self._quantized_vecs

    def item_index(self) -> dict:
        """sem-id tuple -> corpus item id (cached; O(N) Python, built on
        the staging thread via the heads' prepare_snapshot hooks, never
        on the serving batcher)."""
        if self._item_index is None:
            self._item_index = {
                tuple(int(c) for c in row): i
                for i, row in enumerate(self.item_sem_ids)
            }
        return self._item_index

    # -- atomic on-disk format -----------------------------------------------

    def filename(self) -> str:
        return f"{FILE_PREFIX}{self.version}{FILE_SUFFIX}"

    def save(self, directory: str) -> str:
        """Write ``catalog-<version>.npz`` atomically; returns the path."""
        os.makedirs(directory, exist_ok=True)
        final = os.path.join(directory, self.filename())
        payload = dict(
            item_sem_ids=self.item_sem_ids,
            codebook_size=np.int64(self.codebook_size),
            capacity=np.int64(self.capacity),
            version=np.str_(self.version),
        )
        if self.item_vecs is not None:
            payload["item_vecs"] = self.item_vecs
        if self.item_text_tokens is not None:
            payload["item_text_tokens"] = self.item_text_tokens
        fd, tmp = tempfile.mkstemp(
            prefix=self.filename() + ".", suffix=".tmp", dir=directory
        )
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)  # atomic publish under the final name
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return final

    @classmethod
    def load(cls, path: str) -> "CatalogSnapshot":
        """Load + integrity-verify: the recorded version must equal the
        hash recomputed from the loaded arrays."""
        try:
            with np.load(path, allow_pickle=False) as z:
                snap = cls(
                    item_sem_ids=z["item_sem_ids"],
                    codebook_size=int(z["codebook_size"]),
                    item_vecs=z["item_vecs"] if "item_vecs" in z else None,
                    item_text_tokens=(
                        z["item_text_tokens"] if "item_text_tokens" in z else None
                    ),
                    capacity=int(z["capacity"]),
                    version=str(z["version"]),
                )
        except CatalogIntegrityError:
            raise
        except Exception as e:  # unreadable/truncated/garbled archive
            raise CatalogIntegrityError(f"{path}: unreadable snapshot: {e!r}") from e
        want = _content_version(
            snap.item_sem_ids, snap.codebook_size,
            snap.item_vecs, snap.item_text_tokens,
        )
        if snap.version != want:
            raise CatalogIntegrityError(
                f"{path}: content hash {want} != recorded version "
                f"{snap.version} — snapshot is garbled"
            )
        if snap.capacity < 1:
            raise CatalogIntegrityError(f"{path}: invalid capacity {snap.capacity}")
        return snap

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return (
            f"CatalogSnapshot(version={self.version}, n_items={self.n_items}, "
            f"depth={self.depth}, K={self.codebook_size}, "
            f"capacity={self.capacity})"
        )


def list_snapshots(directory: str) -> list[str]:
    """Snapshot files in ``directory``, oldest-mtime first (the watcher
    stages the newest). Non-snapshot files are ignored."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if name.startswith(FILE_PREFIX) and name.endswith(FILE_SUFFIX):
            out.append(os.path.join(directory, name))
    out.sort(key=lambda p: (os.path.getmtime(p), p))
    return out
