"""Live catalog subsystem: the item corpus as a versioned, swappable
RUNTIME artifact.

- `tensor_trie.TensorTrie` — the legal-item trie flattened into int32
  tensors (node child CSR offsets + sorted keys, padded to a static
  capacity ladder) and registered as a jax pytree, so constrained decode
  takes it as a runtime OPERAND instead of baking tables into every
  executable ("Vectorizing the Trie", arxiv 2602.22647).
- `snapshot.CatalogSnapshot` — sem-id tuples + corpus lookup + optional
  COBRA item-tower embeddings, content-hash versioned, with an atomic
  on-disk format the serving watcher hot-swaps between micro-batches
  (genrec_tpu/serving/catalog.py).

See docs/SERVING.md ("Live catalog") for swap semantics.
"""

from genrec_tpu.catalog.snapshot import (
    CatalogIntegrityError,
    CatalogSnapshot,
    list_snapshots,
)
from genrec_tpu.catalog.tensor_trie import (
    MIN_CAPACITY,
    PAD_KEY,
    TensorTrie,
    capacity_for,
)

__all__ = [
    "CatalogIntegrityError",
    "CatalogSnapshot",
    "MIN_CAPACITY",
    "PAD_KEY",
    "TensorTrie",
    "capacity_for",
    "list_snapshots",
]
