"""TensorTrie: the legal-item trie as a device-resident RUNTIME OPERAND.

The `ops/trie` representations (DenseTrie boolean tables, PackedTrie
sorted-key arrays) are correct and fast — but every serving executable
that closes over one bakes the tables in as XLA literals: a catalog
change recompiles every bucket, executable size scales with the corpus,
and graftlint's `constant_bake` rule carried the debt as two baseline
suppressions. "Vectorizing the Trie" (PAPERS.md, arxiv 2602.22647) gives
the fix: flatten the trie into plain int32 tensors and pass them as
runtime ARGUMENTS, with gather/segment ops replacing pointer chasing, so
ONE compiled executable serves any catalog snapshot.

Encoding — a rank-based child CSR, one row per depth:

- ``keys``    (D, C) int32 — step t's sorted unique ``parent_rank * K +
  code`` pairs (the CSR values, parent recoverable as ``key // K``),
  padded to the static capacity C with ``PAD_KEY`` (int32 max, sorting
  above every real key so binary search ignores the padding);
- ``offsets`` (D, C+1) int32 — the CSR row index: node p's children at
  step t occupy ``keys[t, offsets[t, p]:offsets[t, p+1]]``. Derived
  from ``keys`` at build time; carried for segment reads and stats
  (``n_nodes`` per step is ``offsets[t, -1]``).

A prefix is represented by its RANK among the sorted valid prefixes of
that length (exactly PackedTrie's representation, so the two agree
rank-for-rank along every valid path); the dead-prefix sentinel is the
static capacity C, whose candidate keys exceed every storable key.
``legal_mask``/``advance`` are vmapped ``searchsorted`` gathers — no
host sync, no Python loops — and the ragged variants gather the PER-ROW
key row directly (``keys[steps]``) instead of the compute-all-depths
row-select the heterogeneous-shape tries need.

Capacity ladder: C is padded UP to a static rung (geometric, x4 from
``MIN_CAPACITY``) so catalog snapshots of similar size share an aval —
swapping them into a compiled executable is a pure operand change.
Growth past a rung changes the aval and is the ONLY recompile, done AOT
on the serving engine's staging thread (serving/catalog.py).

TensorTrie is registered as a jax pytree (arrays are children,
``codebook_size`` is static aux data), so it can be passed straight
through ``jax.jit`` boundaries, lowered from ShapeDtypeStructs, and
duck-types the DenseTrie/PackedTrie interface (``legal_mask`` /
``advance`` / ``depth`` / ``codebook_size``) everywhere the models
already take a ``trie`` argument.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: Padding key: int32 max sorts above every real key (< (C+1) * K, checked
#: at build), so searchsorted over a padded row never lands on padding.
PAD_KEY = np.iinfo(np.int32).max

#: Smallest capacity rung. Rungs grow geometrically (x4): snapshots whose
#: node counts land in the same rung share an executable.
MIN_CAPACITY = 64
CAPACITY_GROWTH = 4


def capacity_for(n_nodes: int) -> int:
    """The static capacity rung covering ``n_nodes`` trie nodes."""
    c = MIN_CAPACITY
    while c < n_nodes:
        c *= CAPACITY_GROWTH
    return c


@jax.tree_util.register_pytree_node_class
class TensorTrie:
    """Flat tensor trie over sem-id tuples of depth D, codebook size K.

    ``keys``/``offsets`` may be numpy arrays, jax arrays, tracers, or
    ShapeDtypeStructs — the same object flows from the snapshot builder
    through ``jax.jit`` lowering into the compiled call.
    """

    def __init__(self, keys, offsets, codebook_size: int, weights=None):
        self.keys = keys          # (D, C) int32, per-row sorted, PAD_KEY-padded
        self.offsets = offsets    # (D, C+1) int32 CSR row index
        self.codebook_size = int(codebook_size)
        # Per-node draft weight, aligned with ``keys``: by default the
        # number of complete legal tuples below each node (leaf counts —
        # the corpus-popularity signal the speculative drafter ranks
        # trie-legal children by, ops/spec_tree.py). ``build`` can
        # aggregate per-item scores instead (e.g. retrieval-head item
        # scores mapped through the corpus index). Zeros when the
        # builder has no signal: the drafter then ranks by code order.
        if weights is None:
            weights = np.zeros(np.shape(keys), np.float32)
        self.weights = weights    # (D, C) float32, 0 on padding rows

    # -- pytree protocol (arrays are leaves, K is static) --------------------

    def tree_flatten(self):
        return (self.keys, self.offsets, self.weights), (self.codebook_size,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, offsets, weights = children
        return cls(keys, offsets, aux[0], weights)

    @property
    def depth(self) -> int:
        return int(self.keys.shape[0])

    @property
    def capacity(self) -> int:
        return int(self.keys.shape[1])

    # -- build ---------------------------------------------------------------

    @classmethod
    def build(cls, valid_ids: np.ndarray, codebook_size: int,
              capacity: int | None = None,
              item_weights: np.ndarray | None = None) -> "TensorTrie":
        """Flatten (N, D) legal tuples into the padded runtime encoding.

        ``capacity`` pins an explicit rung (it must cover the widest
        step); by default the smallest ladder rung covering the catalog
        is used, so same-rung snapshots share executables.

        ``item_weights`` (N,) optionally scores each tuple (e.g. a
        retrieval head's item scores through the corpus index); each
        trie node's draft weight is the SUM over the tuples below it.
        Default: every tuple weighs 1, so node weight == leaf count
        (corpus popularity), the zero-cost drafter signal.
        """
        valid_ids = np.asarray(valid_ids, np.int64)
        if valid_ids.ndim != 2 or valid_ids.size == 0:
            raise ValueError(f"need a (N, D) tuple table, got {valid_ids.shape}")
        N, D = valid_ids.shape
        K = int(codebook_size)
        if valid_ids.min() < 0 or valid_ids.max() >= K:
            raise ValueError(f"sem-id codes outside [0, {K})")
        w_items = (
            np.ones(N, np.float64) if item_weights is None
            else np.asarray(item_weights, np.float64).reshape(N)
        )
        step_keys, step_weights = [], []
        rank = np.zeros(N, np.int64)
        for t in range(D):
            k = rank * K + valid_ids[:, t]
            uniq = np.unique(k)
            step_keys.append(uniq)
            rank = np.searchsorted(uniq, k)
            # Node weight = sum of item weights below the node (leaf
            # count under the default all-ones weighting).
            step_weights.append(
                np.bincount(rank, weights=w_items, minlength=len(uniq))
            )
        n_max = max(len(u) for u in step_keys)
        C = capacity_for(n_max) if capacity is None else int(capacity)
        if C < n_max:
            raise ValueError(f"capacity {C} < {n_max} trie nodes at the widest step")
        # The dead-prefix sentinel C must still produce int32 candidate
        # keys below PAD_KEY: (C + 1) * K is the largest candidate formed.
        if (C + 1) * K > PAD_KEY:
            max_c = PAD_KEY // K - 1
            rung = MIN_CAPACITY
            while rung * CAPACITY_GROWTH <= max_c:
                rung *= CAPACITY_GROWTH
            raise ValueError(
                f"capacity {C} x codebook {K} overflows int32 keys: the "
                f"largest candidate key (C + 1) * K = {(C + 1) * K} exceeds "
                f"PAD_KEY = {PAD_KEY}. The largest usable capacity for this "
                f"codebook is {max_c} (ladder rung {rung}); rebuild with "
                f"capacity <= {rung} (which must still cover the widest "
                "step), shrink the catalog snapshot, or wait for wider "
                "(int64) trie keys — tracked on the ROADMAP."
            )
        keys = np.full((D, C), PAD_KEY, np.int32)
        offsets = np.zeros((D, C + 1), np.int32)
        weights = np.zeros((D, C), np.float32)
        for t, uniq in enumerate(step_keys):
            keys[t, : len(uniq)] = uniq
            weights[t, : len(uniq)] = step_weights[t]
            # CSR row starts: node p's children begin where key p*K would
            # insert. Rows past the real node count collapse to empty
            # segments at n_keys (PAD_KEY sorts above every probe).
            offsets[t] = np.searchsorted(uniq, np.arange(C + 1) * K)
        return cls(keys, offsets, K, weights)

    def device(self) -> "TensorTrie":
        """The same trie with its tensors as jax device arrays."""
        return TensorTrie(
            jnp.asarray(self.keys), jnp.asarray(self.offsets),
            self.codebook_size, jnp.asarray(self.weights),
        )

    def n_nodes(self) -> list[int]:
        """Real (unpadded) node count per step — build-time stats only."""
        return [int(np.asarray(self.offsets[t, -1])) for t in range(self.depth)]

    # -- the decode-loop interface (DenseTrie/PackedTrie-compatible) ---------

    def legal_mask(self, prefix_idx: jax.Array, step: int) -> jax.Array:
        """prefix_idx: (...,) ranks -> (..., K) bool of legal next codes."""
        with jax.named_scope("trie_legal_mask"):
            return self._mask_row(self.keys[step], prefix_idx)

    def advance(self, prefix_idx: jax.Array, token: jax.Array, step: int) -> jax.Array:
        """Rank of the extended prefix; dead/illegal -> sentinel capacity."""
        return self._advance_row(self.keys[step], prefix_idx, token)

    def legal_mask_ragged(self, prefix_idx: jax.Array, steps: jax.Array) -> jax.Array:
        """Per-row step operand: prefix_idx (S, ...) + steps (S,) ->
        (S, ..., K). The uniform (D, C) layout lets the row gather
        ``keys[steps]`` replace the compute-all-depths select that
        `ops/trie.legal_mask_ragged` needs for heterogeneous tables."""
        with jax.named_scope("trie_legal_mask_ragged"):
            row_keys = self.keys[steps]  # (S, C)
            return jax.vmap(self._mask_row)(row_keys, prefix_idx)

    def advance_ragged(self, prefix_idx: jax.Array, token: jax.Array,
                       steps: jax.Array) -> jax.Array:
        with jax.named_scope("trie_advance_ragged"):
            row_keys = self.keys[steps]
            return jax.vmap(self._advance_row)(row_keys, prefix_idx, token)

    def child_weights_ragged(self, prefix_idx: jax.Array,
                             steps: jax.Array) -> jax.Array:
        """Draft weight of every candidate child code, per-row step:
        prefix_idx (S, ...) + steps (S,) -> (S, ..., K) float32 — the
        node weight of the extended prefix where it is legal, 0 where it
        is not (the speculative drafter masks illegal codes itself).
        Same searchsorted gather as `legal_mask_ragged`, one extra
        weight-row read."""
        with jax.named_scope("trie_child_weights_ragged"):
            row_keys = self.keys[steps]     # (S, C)
            row_w = self.weights[steps]     # (S, C)

            def one_row(keys_row, w_row, prefix):
                K = self.codebook_size
                cand = prefix[..., None] * K + jnp.arange(K, dtype=jnp.int32)
                pos = jnp.clip(jnp.searchsorted(keys_row, cand), 0,
                               keys_row.shape[0] - 1)
                return jnp.where(keys_row[pos] == cand, w_row[pos], 0.0)

            return jax.vmap(one_row)(row_keys, row_w, prefix_idx)

    # -- shared row kernels (sorted-gather binary search) --------------------

    def _mask_row(self, row_keys: jax.Array, prefix_idx: jax.Array) -> jax.Array:
        K = self.codebook_size
        cand = prefix_idx[..., None] * K + jnp.arange(K, dtype=jnp.int32)
        pos = jnp.clip(jnp.searchsorted(row_keys, cand), 0, row_keys.shape[0] - 1)
        return row_keys[pos] == cand

    def _advance_row(self, row_keys: jax.Array, prefix_idx: jax.Array,
                     token: jax.Array) -> jax.Array:
        C = row_keys.shape[0]
        key = prefix_idx * self.codebook_size + token
        pos = jnp.clip(jnp.searchsorted(row_keys, key), 0, C - 1)
        return jnp.where(row_keys[pos] == key, pos, C).astype(jnp.int32)

    # -- misc ----------------------------------------------------------------

    def aval_signature(self) -> tuple:
        """The shape/dtype facts that decide executable compatibility: a
        snapshot whose trie matches this signature swaps into a compiled
        executable as a pure operand change (no recompile)."""
        return (
            tuple(int(s) for s in self.keys.shape),
            tuple(int(s) for s in self.offsets.shape),
            tuple(int(s) for s in self.weights.shape),
            self.codebook_size,
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return (
            f"TensorTrie(depth={self.keys.shape[0]}, "
            f"capacity={self.keys.shape[1]}, K={self.codebook_size})"
        )
