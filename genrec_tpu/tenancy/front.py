"""TenantFront: per-tenant isolation over one serving engine.

The engine (PR 5..19) already carries every mechanism a tenancy layer
needs — versioned per-head catalogs, an enforced HBM ledger, per-head
SLO shed, response provenance, rooted traces — but nothing GROUPS them:
a head is an implementation detail, a tenant is a contract. This front
speaks the engine's exact ``submit() -> Future`` surface (the same
duck-type the `FleetRouter` exposes, so it stacks on either) and owns
the grouping:

- **Binding**: each tenant binds exactly one head (1:1 — the head IS the
  tenant's model surface), its own catalog directory (a per-tenant
  `CatalogWatcher` publishes corpus snapshots independently), its own
  `SLOTarget`, and an HBM sub-budget carved out of the engine ledger's
  per-head groups (``ledger()`` reports per-tenant sub-totals that sum
  to the engine total — the check_tenancy invariant).
- **Admission/shed**: per-tenant in-flight accounting (submitted minus
  resolved, bounded by ``max_inflight``) plus a per-tenant `SLOMonitor`
  fed from the metrics rings' TENANT key
  (`ServingMetrics.record_tenant_response`) — so a hot tenant sheds the
  typed `OverloadError` at THIS layer while co-hosted tenants' requests
  never queue behind it. Engine-level per-head shed stays as the inner
  backstop.
- **Experiments**: per-tenant A/B routing + shadow mirroring
  (tenancy/experiment.py) over duck-typed submit targets, so a PR 19
  canary replica graduates into an arm without new serving surface.
- **Attribution**: when the front is the outermost submitter it mints
  the request's lineage and stamps ``tenant=`` on the root "request"
  span — `trace_report.py --critical-path --tenant <t>` filters on it.

Threading: ``submit()`` runs on caller threads; completion callbacks on
the engine's batcher thread. One lock guards the tenant table and
counters; never held across an engine call or a Future result.

Layering: L7 beside fleet/ and disagg/ — imports serving/fleet/obs;
nothing imports tenancy.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import Counter
from concurrent.futures import Future
from typing import Optional

from genrec_tpu.obs.flight_recorder import get_flight_recorder
from genrec_tpu.obs.slo import SLOMonitor, SLOTarget
from genrec_tpu.obs.spans import NULL_TRACER, SpanTracer, TraceContext
from genrec_tpu.serving.catalog import CatalogWatcher
from genrec_tpu.serving.metrics import ServingMetrics
from genrec_tpu.serving.types import OverloadError, Request
from genrec_tpu.tenancy.experiment import Experiment, ExperimentConfig

#: stats()["tenancy"] counter keys, in emission order (obs/export.py
#: types each as a Prometheus counter; inflight/p99_ms/shedding are the
#: gauges).
TENANT_COUNTERS = (
    "submitted", "completed", "failed", "shed", "shadow_mirrored",
    "exp_arm_a", "exp_arm_b",
)


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """One tenant's contract: head binding + isolation knobs.

    ``slo`` drives the per-tenant shed state machine (None = this tenant
    never sheds at the front); ``max_inflight`` is the hard queue-
    accounting bound (admission fails typed once this many submissions
    are unresolved); ``hbm_budget_bytes`` is the ledger sub-budget
    ``ledger()`` audits the bound head's group against;
    ``catalog_dir`` gets a dedicated CatalogWatcher.
    """

    name: str
    head: str
    slo: Optional[SLOTarget] = None
    catalog_dir: Optional[str] = None
    hbm_budget_bytes: Optional[int] = None
    max_inflight: Optional[int] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant needs a name")
        if self.max_inflight is not None and self.max_inflight <= 0:
            raise ValueError(f"max_inflight must be positive, got {self.max_inflight}")


class _Tenant:
    """Mutable per-tenant state (guarded by the front's lock)."""

    __slots__ = ("cfg", "counters", "inflight", "watcher", "experiment",
                 "next_poll", "shedding")

    def __init__(self, cfg: TenantConfig):
        self.cfg = cfg
        self.counters: Counter = Counter()
        self.inflight = 0
        self.watcher: Optional[CatalogWatcher] = None
        self.experiment: Optional[Experiment] = None
        self.next_poll = 0.0
        self.shedding = False  # front-observed SLO state (for transitions)


class TenantFront:
    """The engine surface, tenant-aware. See module docstring."""

    def __init__(self, engine, tenants=(), tracer: Optional[SpanTracer] = None,
                 slo_poll_s: float = 0.05,
                 logger: Optional[logging.Logger] = None):
        self._engine = engine
        self._lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        self._by_head: dict[str, str] = {}
        self._slo: Optional[SLOMonitor] = None
        self._slo_poll_s = float(slo_poll_s)
        self._log = logger or logging.getLogger("genrec_tpu")
        self._flight = get_flight_recorder().scoped("tenant_front")
        if tracer is None:
            tracer = getattr(engine, "tracer", None)
        self._tracer = tracer if tracer is not None else NULL_TRACER
        # Tenant p99 rings live in the ENGINE's metrics when it has them
        # (one ring store per serving process); a router front without
        # metrics gets a private store — the rings are front-fed either
        # way (record_tenant_response).
        self._metrics = getattr(engine, "metrics", None)
        if self._metrics is None:
            self._metrics = ServingMetrics()
        for cfg in tenants:
            self.add_tenant(cfg)

    # -- tenant table --------------------------------------------------------

    def add_tenant(self, cfg: TenantConfig) -> None:
        """Bind a tenant. Head bindings are exclusive (1:1): the head is
        the tenant's model surface, and per-head engine metrics/SLO
        attribution would smear if two tenants shared one."""
        with self._lock:
            if cfg.name in self._tenants:
                raise ValueError(f"tenant {cfg.name!r} already bound")
            holder = self._by_head.get(cfg.head)
            if holder is not None:
                raise ValueError(
                    f"head {cfg.head!r} already bound to tenant {holder!r}"
                )
            st = _Tenant(cfg)
            self._tenants[cfg.name] = st
            self._by_head[cfg.head] = cfg.name
            # SLOMonitor's target set is fixed at construction; rebuild
            # with the grown set (shed state restarts clean for everyone
            # — add_tenant is a control-plane op, not a hot-path one).
            targets = {
                name: t.cfg.slo
                for name, t in self._tenants.items() if t.cfg.slo is not None
            }
        if cfg.catalog_dir is not None:
            st.watcher = CatalogWatcher(
                self._engine, cfg.head, cfg.catalog_dir, logger=self._log
            ).start()
        with self._lock:
            self._slo = SLOMonitor(targets) if targets else None
        self._flight.record(
            "tenant_added", tenant=cfg.name, head=cfg.head,
            has_slo=cfg.slo is not None,
            has_catalog_dir=cfg.catalog_dir is not None,
            hbm_budget_bytes=cfg.hbm_budget_bytes,
            max_inflight=cfg.max_inflight,
        )
        self._log.info(
            f"tenancy: tenant {cfg.name!r} bound to head {cfg.head!r}"
        )

    def set_tracer(self, tracer: Optional[SpanTracer]) -> None:
        """Swap tracing live (same contract as the engine/router: build
        fronts and engines on ONE tracer instance so span ids stay one
        id space; None turns front-minted lineage off)."""
        self._tracer = tracer if tracer is not None else NULL_TRACER

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._tenants)

    def tenant_of(self, head: str) -> Optional[str]:
        with self._lock:
            return self._by_head.get(head)

    def stop(self) -> None:
        """Stop the front's own machinery (watchers; running experiments
        are concluded so their reports are not lost). The engine's
        lifecycle belongs to its owner."""
        with self._lock:
            tenants = list(self._tenants.values())
        for st in tenants:
            if st.experiment is not None:
                try:
                    self.conclude_experiment(st.cfg.name)
                except Exception:  # noqa: BLE001 — stop() must not throw
                    self._log.exception(
                        f"tenancy: concluding experiment for {st.cfg.name!r} failed"
                    )
            if st.watcher is not None:
                st.watcher.stop()
                st.watcher = None

    # -- experiments ---------------------------------------------------------

    def start_experiment(self, tenant: str, config: ExperimentConfig,
                         arms: dict, shadow=None) -> Experiment:
        """Register an A/B experiment on ``tenant``'s traffic. ``arms``
        maps {"a": target, "b": target} to duck-typed submit targets
        (engines, routers, pinned rollout replicas); ``shadow`` is an
        optional third target that is mirrored to but never answered
        from."""
        for arm_name, target in dict(arms).items():
            if not callable(getattr(target, "submit", None)):
                raise ValueError(f"arm {arm_name!r} target has no submit()")
        if shadow is not None and not callable(getattr(shadow, "submit", None)):
            raise ValueError("shadow target has no submit()")
        exp = Experiment(config, arms, shadow)
        with self._lock:
            st = self._tenants[tenant]
            if st.experiment is not None:
                raise ValueError(
                    f"tenant {tenant!r} already runs experiment "
                    f"{st.experiment.config.name!r}"
                )
            st.experiment = exp
        self._flight.record(
            "experiment_started", tenant=tenant, experiment=config.name,
            seed=config.seed, split=config.split, shadow=shadow is not None,
        )
        return exp

    def conclude_experiment(self, tenant: str) -> dict:
        """Detach + conclude the tenant's experiment; returns (and, when
        configured, atomically writes) the exp_report artifact."""
        with self._lock:
            st = self._tenants[tenant]
            exp, st.experiment = st.experiment, None
        if exp is None:
            raise ValueError(f"tenant {tenant!r} has no running experiment")
        data = exp.conclude()
        summary = data["summary"]
        self._flight.record(
            "experiment_concluded", tenant=tenant,
            experiment=data["experiment"], n_records=data["n_records"],
            routed_a=summary["routed_a"], routed_b=summary["routed_b"],
            shadow_mirrored=summary["shadow_mirrored"],
            shadow_errors=summary["shadow_errors"],
            report_path=exp.config.report_path,
        )
        return data

    # -- request path --------------------------------------------------------

    def submit(self, req: Request) -> Future:
        """The engine surface, tenant-aware: typed `OverloadError` names
        the shedding TENANT; heads no tenant bound stay untouched
        (pass-through), so tenanted and plain traffic co-host."""
        with self._lock:
            tenant = self._by_head.get(req.head)
            st = self._tenants.get(tenant) if tenant else None
        if st is None:
            return self._engine.submit(req)
        self._poll_slo(tenant, st)
        with self._lock:
            cfg = st.cfg
            if cfg.max_inflight is not None and st.inflight >= cfg.max_inflight:
                st.counters["shed"] += 1
                reason = f"inflight {st.inflight} >= max_inflight {cfg.max_inflight}"
                shed = True
            elif self._slo is not None and cfg.slo is not None \
                    and self._slo.is_shedding(tenant):
                st.counters["shed"] += 1
                reason = self._slo.shed_reason(tenant)
                shed = True
            else:
                shed = False
        if shed:
            raise OverloadError(f"tenant {tenant!r} shedding: {reason}")
        exp = st.experiment
        target, arm = self._engine, None
        if exp is not None:
            arm, target = exp.route(req.user_id)
        tracer = self._tracer
        minted = None
        if req.trace is None and tracer.enabled:
            # Outermost submit: mint the lineage; the root "request"
            # span (recorded when the caller's future resolves) carries
            # the tenant attribution the trace reports filter on.
            tid = tracer.new_trace()
            root = tracer.allocate_span_id()
            req = dataclasses.replace(
                req, trace=TraceContext(tid, root, "tenant_front")
            )
            minted = (tid, root)
        t_sub = time.monotonic()
        try:
            fut = target.submit(req)
        except OverloadError:
            # The inner engine/router shed this head — count it against
            # the tenant (its callers see the identical typed error).
            with self._lock:
                st.counters["shed"] += 1
            raise
        with self._lock:
            st.counters["submitted"] += 1
            if arm is not None:
                st.counters[f"exp_arm_{arm}"] += 1
            st.inflight += 1
        head = req.head

        def _done(f, tenant=tenant, st=st, t_sub=t_sub, minted=minted,
                  head=head, arm=arm):
            dt = time.monotonic() - t_sub
            try:
                err = f.exception()
            except Exception:  # noqa: BLE001 — cancelled future
                err = True
            with self._lock:
                st.inflight -= 1
                st.counters["failed" if err else "completed"] += 1
            if not err:
                self._metrics.record_tenant_response(tenant, dt)
            if minted is not None:
                attrs = dict(
                    head=head, origin="tenant_front",
                    component="tenant_front", tenant=tenant,
                    outcome="error" if err else "ok",
                )
                if arm is not None:
                    attrs["exp_arm"] = arm
                tracer.record_span(
                    "request", minted[0], t_sub, time.monotonic(),
                    span_id=minted[1], **attrs,
                )

        fut.add_done_callback(_done)
        if exp is not None and exp.shadow is not None:
            self._mirror_shadow(st, exp, req, arm, fut, t_sub)
        return fut

    def _mirror_shadow(self, st: _Tenant, exp: Experiment, req: Request,
                       arm: str, primary_fut: Future, t_sub: float) -> None:
        """Submit a COPY to the shadow target and pair its answer with
        the primary's into the experiment record. The shadow future is
        consumed HERE — its result (or failure) can never surface in the
        caller's future. The copy drops the caller's trace context: the
        candidate's spans must not pollute the primary's critical path
        (the shadow run roots its own trace inside its engine)."""
        shadow_req = dataclasses.replace(req, trace=None)
        holder: dict = {}
        hlock = threading.Lock()
        user_id = int(req.user_id)

        def _maybe_record():
            with hlock:
                if "primary" not in holder or "shadow" not in holder:
                    return
                p_kind, p_val = holder["primary"]
                s_kind, s_val = holder["shadow"]
            if p_kind != "ok":
                return  # primary failed: nothing to attribute against
            if s_kind == "ok":
                exp.record_pair(user_id, arm, p_val, shadow_resp=s_val,
                                t_submit=t_sub)
            else:
                exp.record_pair(user_id, arm, p_val, shadow_error=s_val,
                                t_submit=t_sub)

        def _settle(key):
            def cb(f):
                try:
                    val = ("ok", f.result())
                except BaseException as e:  # noqa: BLE001 — recorded, never raised
                    val = ("err", repr(e))
                with hlock:
                    holder[key] = val
                _maybe_record()
            return cb

        primary_fut.add_done_callback(_settle("primary"))
        try:
            shadow_fut = exp.shadow.submit(shadow_req)
        except Exception as e:  # noqa: BLE001 — a shedding candidate is data
            with hlock:
                holder["shadow"] = ("err", repr(e))
            _maybe_record()
            return
        with self._lock:
            st.counters["shadow_mirrored"] += 1
        shadow_fut.add_done_callback(_settle("shadow"))

    # -- SLO plumbing --------------------------------------------------------

    def _poll_slo(self, tenant: str, st: _Tenant) -> None:
        """Opportunistic per-tenant SLO evaluation on the submit path
        (rate-limited; no background thread — an idle tenant needs no
        shed decision). Feeds the tenant's windowed p99 (tenant metrics
        ring) + live in-flight depth; fires the tenant_shed_* flight
        events on transitions."""
        if self._slo is None or st.cfg.slo is None:
            return
        now = time.monotonic()
        with self._lock:
            if now < st.next_poll:
                return
            st.next_poll = now + self._slo_poll_s
            depth = st.inflight
        p99 = self._metrics.recent_p99_ms(st.cfg.slo.window_s, tenant=tenant)
        shedding = self._slo.observe(
            tenant, p99_ms=p99, queue_depth=depth, now=now
        )
        with self._lock:
            was, st.shedding = st.shedding, shedding
        if shedding and not was:
            self._flight.record(
                "tenant_shed_started", tenant=tenant,
                reason=self._slo.shed_reason(tenant), inflight=depth,
                p99_ms=None if p99 is None else round(p99, 3),
            )
        elif was and not shedding:
            self._flight.record("tenant_shed_stopped", tenant=tenant)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """{"tenancy": {tenant: counters+gauges}, "experiments": {...}}.
        Counter leaves are typed as Prometheus counters through
        obs/export.py; ``inflight``/``p99_ms``/``shedding`` are gauges."""
        with self._lock:
            items = sorted(self._tenants.items())
        tenancy: dict = {}
        experiments: dict = {}
        for name, st in items:
            with self._lock:
                entry = {k: st.counters.get(k, 0) for k in TENANT_COUNTERS}
                entry["inflight"] = st.inflight
                entry["shedding"] = st.shedding
            if st.cfg.slo is not None:
                p99 = self._metrics.recent_p99_ms(
                    st.cfg.slo.window_s, tenant=name
                )
                if p99 is not None:
                    entry["p99_ms"] = round(p99, 3)
            tenancy[name] = entry
            if st.experiment is not None:
                experiments[st.experiment.config.name] = st.experiment.snapshot()
        out: dict = {"tenancy": tenancy}
        if experiments:
            out["experiments"] = experiments
        if self._slo is not None:
            out["slo"] = self._slo.snapshot()
        return out

    def ledger(self) -> dict:
        """Per-tenant HBM sub-totals carved from the engine ledger's
        per-head groups, plus the unassigned remainder — built so the
        parts PROVABLY sum back to the engine total (the check_tenancy
        invariant): Σ tenant operand_bytes + unassigned_operand_bytes +
        transient_peak_bytes == total_bytes (one executable runs at a
        time, so the cross-group transient peak is a single shared
        term, exactly as `MemoryLedger.summary` accounts it)."""
        mem = getattr(self._engine, "memory", None)
        if mem is None:
            return {}
        summary = mem.summary()
        heads = summary["heads"]
        with self._lock:
            by_head = {st.cfg.head: name for name, st in self._tenants.items()}
            budgets = {name: st.cfg.hbm_budget_bytes
                       for name, st in self._tenants.items()}
        tenants: dict = {}
        unassigned = 0
        for gname in sorted(heads):
            g = heads[gname]
            tname = by_head.get(gname)
            if tname is None:
                unassigned += g["operand_bytes"]
                continue
            entry = {
                "head": gname,
                "operand_bytes": g["operand_bytes"],
                "transient_peak_bytes": g["transient_peak_bytes"],
                "total_bytes": g["total_bytes"],
            }
            budget = budgets.get(tname)
            if budget is not None:
                entry["budget_bytes"] = int(budget)
                entry["over_budget"] = g["total_bytes"] > int(budget)
            tenants[tname] = entry
        return {
            "tenants": tenants,
            "unassigned_operand_bytes": unassigned,
            "transient_peak_bytes": max(
                (h["transient_peak_bytes"] for h in heads.values()), default=0
            ),
            "total_bytes": summary["total_bytes"],
        }
