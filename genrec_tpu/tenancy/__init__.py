"""Multi-tenant serving plane (L7): per-tenant isolation over one
engine + the A/B / shadow experimentation plane. `TenantFront` speaks
the engine's exact ``submit() -> Future`` surface; see
docs/SERVING.md ("Multi-tenancy + experiments")."""

from genrec_tpu.tenancy.experiment import (
    ARMS,
    Experiment,
    ExperimentConfig,
    bucket_arm,
)
from genrec_tpu.tenancy.front import TENANT_COUNTERS, TenantConfig, TenantFront

__all__ = [
    "ARMS",
    "Experiment",
    "ExperimentConfig",
    "TENANT_COUNTERS",
    "TenantConfig",
    "TenantFront",
    "bucket_arm",
]
