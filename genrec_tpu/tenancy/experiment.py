"""Experimentation plane: deterministic A/B bucketing + shadow mirroring.

The serving provenance fields (``Response.params_step`` /
``catalog_version`` / ``request_id``, PR 7/9) make offline attribution
possible; this module adds the ONLINE half — which params version a user
sees — as a property the infra guarantees rather than the caller
remembers:

- **Bucketing** is a pure function of ``(seed, user_id)``:
  ``sha256(f"{seed}:{user_id}")``'s first 8 bytes as a uniform draw on
  [0, 1) against the split. No process state, no RNG object — the same
  user lands in the same arm across restarts, hosts, and languages with
  a sha256 library, and the split is exact within binomial tolerance
  (both property-tested in tests/test_tenancy.py).
- **Arms** are duck-typed submit targets (anything with
  ``submit(req) -> Future``): a second `ServingEngine`, a
  `FleetRouter`, or one pinned replica of the PR 19 rollout machinery —
  a canary that survived its guard window graduates into arm "b" by
  being registered here, no new serving surface.
- **Shadow** is a third target that sees a COPY of every routed request
  and whose responses are recorded but never returned: the caller's
  future is always the primary arm's future, and the shadow future is
  consumed internally (exceptions included — a broken candidate shows
  up as ``shadow_errors`` in the report, never in a caller's result).

The report (``snapshot()`` / ``conclude()``) pairs each primary response
with its shadow response via the provenance fields into ``exp_report``
records — the artifact offline analysis joins against — written
atomically (tmp + ``os.replace``, the checkpoint/catalog discipline).

Layering: tenancy imports serving/fleet/obs; nothing imports tenancy.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from collections import deque
from typing import Optional

#: Arm names, in registration order. Bucketing maps [0, split) -> "a".
ARMS = ("a", "b")


def bucket_arm(seed: int, user_id: int, split: float = 0.5) -> str:
    """Deterministic A/B assignment for ``(seed, user_id)``.

    The first 8 bytes of ``sha256(f"{seed}:{user_id}")`` as a uniform
    u64 draw: ``draw / 2**64 < split`` -> arm "a". Stable across
    processes and restarts (no RNG state), split-exact in expectation.
    """
    digest = hashlib.sha256(f"{int(seed)}:{int(user_id)}".encode()).digest()
    draw = int.from_bytes(digest[:8], "big") / 2.0**64
    return "a" if draw < float(split) else "b"


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """One A/B experiment on one tenant's traffic.

    ``split`` is arm "a"'s traffic share. ``report_path`` (optional)
    is where ``conclude()`` writes the exp_report JSON artifact.
    ``max_records`` bounds the paired-comparison ring (oldest evicted;
    counters keep the lifetime totals).
    """

    name: str
    seed: int
    split: float = 0.5
    report_path: Optional[str] = None
    max_records: int = 8192

    def __post_init__(self):
        if not 0.0 <= self.split <= 1.0:
            raise ValueError(f"split {self.split} outside [0, 1]")


def _provenance(resp) -> dict:
    """The response fields offline attribution joins on."""
    return {
        "request_id": getattr(resp, "request_id", None),
        "params_step": getattr(resp, "params_step", None),
        "catalog_version": getattr(resp, "catalog_version", None),
        "replica_id": getattr(resp, "replica_id", None),
        "items": [int(x) for x in getattr(resp, "items", [])],
    }


class Experiment:
    """Routing + recording state for one running experiment.

    Owned by the `TenantFront` (which counts arm routes and mirrors the
    shadow copies); thread-safe — callbacks land from batcher threads.
    """

    def __init__(self, config: ExperimentConfig, arms: dict,
                 shadow=None):
        missing = [a for a in ARMS if a not in arms]
        if missing:
            raise ValueError(f"experiment {config.name!r} missing arms {missing}")
        self.config = config
        self.arms = dict(arms)
        self.shadow = shadow
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=config.max_records)
        self._routed = {a: 0 for a in ARMS}
        self._shadow_mirrored = 0
        self._shadow_errors = 0
        self._shadow_mismatches = 0

    # -- routing -------------------------------------------------------------

    def route(self, user_id: int):
        """(arm_name, submit_target) for this user — pure bucketing."""
        arm = bucket_arm(self.config.seed, user_id, self.config.split)
        with self._lock:
            self._routed[arm] += 1
        return arm, self.arms[arm]

    # -- recording -----------------------------------------------------------

    def record_pair(self, user_id: int, arm: str, primary,
                    shadow_resp=None, shadow_error: Optional[str] = None,
                    t_submit: Optional[float] = None) -> None:
        """One completed (primary, shadow) pair: provenance from both
        sides plus the headline comparison (did the candidate agree?).
        ``shadow_resp`` is None when no shadow target is registered or
        the mirror failed (``shadow_error`` carries the refusal)."""
        rec = {
            "user_id": int(user_id),
            "arm": arm,
            "primary": _provenance(primary),
        }
        if t_submit is not None:
            rec["t_submit"] = float(t_submit)
        if shadow_resp is not None:
            rec["shadow"] = _provenance(shadow_resp)
            rec["items_match"] = rec["shadow"]["items"] == rec["primary"]["items"]
        elif shadow_error is not None:
            rec["shadow_error"] = shadow_error
        with self._lock:
            if shadow_resp is not None:
                self._shadow_mirrored += 1
                if not rec["items_match"]:
                    self._shadow_mismatches += 1
            elif shadow_error is not None:
                self._shadow_errors += 1
            self._records.append(rec)

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Numeric summary (flattens into stats()/Prometheus)."""
        with self._lock:
            return {
                "seed": self.config.seed,
                "split": self.config.split,
                "routed_a": self._routed["a"],
                "routed_b": self._routed["b"],
                "shadow_mirrored": self._shadow_mirrored,
                "shadow_errors": self._shadow_errors,
                "shadow_mismatches": self._shadow_mismatches,
            }

    def report(self) -> dict:
        """The full exp_report payload: summary + paired records."""
        with self._lock:
            records = list(self._records)
        return {
            "experiment": self.config.name,
            "summary": self.snapshot(),
            "n_records": len(records),
            "records": records,
        }

    def conclude(self) -> dict:
        """Final report; written atomically when ``report_path`` is set
        (tmp + os.replace — a reader can never observe a half-written
        artifact, same as checkpoints/catalog snapshots)."""
        data = self.report()
        path = self.config.report_path
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(data, fh, indent=1, sort_keys=True)
            os.replace(tmp, path)
        return data
