"""Disaggregated serving: prefill/decode worker pools with typed
KV-page handoff (docs/architecture.md L7 beside fleet, docs/SERVING.md
"Disaggregated serving").

COBRA's history prefill and its suffix-step decode have completely
different arithmetic-intensity profiles (TPLA, arxiv 2508.15881); this
package splits them into role-specialized pools joined by a typed,
self-describing `KVHandoff` — the refcounted page run + post-prefill
slot-state snapshot the PR-11 prefix cache already retains, at the same
page granularity Ragged Paged Attention made the pool's native unit:

- `handoff` — `KVHandoff` + the pinned wire format; validation skew is
  a typed `HandoffRefusedError`, worker loss a typed `WorkerLostError`.
- `transport` — `KVTransport` with an in-process zero-copy
  implementation (shared page bank, COW `admit_shared` across pools)
  and a serializing host-roundtrip implementation that pins the wire
  bytes and measures transfer cost — the cross-host backend is a
  transport swap, not a redesign.
- `workers` — `PrefillWorker` (admission + the AOT prefill bucket grid
  + per-worker prefix cache) and `DecodeWorker` (slot-level continuous
  batching over decode-only executables, per-worker `MemoryLedger`
  budget enforced at warmup).
- `net` — the cross-host tier: `SocketTransport` (the serializing wire
  bytes over length-prefixed TCP frames), `RemoteDecodeWorker` (the
  front-side proxy duck-typing the decode-worker surface, with a
  per-peer send thread so prefill never blocks on a slow host) and
  `serve_decode_host`/`spawn_decode_host` (the decode-host process
  runtime). Peer death — kill -9 mid-frame included — reaps through
  the same typed at-most-once re-submit as an in-process worker kill.
- `front` — `DisaggFront`: the engine's exact `submit() -> Future`
  surface, request -> prefill pool -> decode pool routing, at-most-once
  typed re-submit on worker death, drain that completes in-flight
  handoffs, and `role_pool()` adapters so `fleet.Autoscaler` scales the
  two roles independently (prefill on queue depth, decode on slot
  occupancy).

The co-located `ServingEngine` stays the default; disagg is opt-in per
head. Layering: disagg imports serving/obs (and core for the signal
guard); nothing imports disagg.
"""

from genrec_tpu.disagg import chaosnet
from genrec_tpu.disagg.front import DisaggFront
from genrec_tpu.disagg.handoff import (
    DisaggError,
    HandoffRefusedError,
    KVHandoff,
    WIRE_VERSION,
    WorkerLostError,
    pack_handoff,
    unpack_handoff,
)
from genrec_tpu.disagg.net import (
    RemoteDecodeWorker,
    SocketTransport,
    serve_decode_host,
    spawn_decode_host,
)
from genrec_tpu.disagg.transport import (
    InProcessTransport,
    KVTransport,
    SerializingTransport,
)
from genrec_tpu.disagg.workers import DecodeWorker, Flight, PrefillWorker

__all__ = [
    "DecodeWorker",
    "DisaggError",
    "DisaggFront",
    "Flight",
    "HandoffRefusedError",
    "InProcessTransport",
    "KVHandoff",
    "KVTransport",
    "PrefillWorker",
    "RemoteDecodeWorker",
    "SerializingTransport",
    "SocketTransport",
    "WIRE_VERSION",
    "WorkerLostError",
    "chaosnet",
    "pack_handoff",
    "serve_decode_host",
    "spawn_decode_host",
    "unpack_handoff",
]
