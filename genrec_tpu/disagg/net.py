"""Cross-host serving: the socket `KVTransport` tier + the decode-host
process runtime.

The serializing transport already pinned the cross-host CONTRACT — every
handoff round-trips through `pack_handoff` bytes — but both roles still
shared one process, one GIL and one device. This module makes the hop
real: prefill stays on the front (with the front's control plane —
submit/route/SLO/autoscaler — untouched), decode workers run in their
OWN OS processes (`serve_decode_host`), and the handoff bytes cross a
TCP socket instead of a function call. Roles genuinely overlap: the
front prefills the next batch while the decode hosts step their slots.

Wire framing (everything on the socket is one of these):

    [8B big-endian frame length][1B type][4B meta length][meta JSON][payload]

- ``HELLO``    (host -> front): the peer's full serving identity —
  worker_id, head, KV layout, kv_dtype, params_step, catalog_version,
  pool geometry, warmup_compiles. The front's proxy validates every
  handoff against THIS, so skew is refused typed before a byte of page
  content crosses the wire.
- ``HANDOFF``  (front -> host): meta carries the request (history /
  user_id — the decode side finalizes against the request) + a
  monotonic ``seq``; payload is the `pack_handoff` bytes, verbatim.
- ``RESULT``   (host -> front): meta is the response provenance
  (bucket, timings, worker ids), payload an ``.npz`` of
  items/scores/sem_ids — bit-exact arrays, not reprinted floats.
- ``REFUSED``  (host -> front): typed failure for one seq
  (HandoffRefusedError backstop, finalize errors) — never silence.
- ``STATS_REQ``/``STATS``: the peer's stats()/pool/recompilation
  counters, so "0 steady-state recompiles" and "pools clean after
  drain" stay checkable ACROSS the process boundary.
- ``SHUTDOWN``/``BYE``: graceful drain handshake; the host exits after
  BYE and the front knows the socket closed clean.

Failure semantics (the disagg contract, held across processes): a peer
that dies mid-frame (kill -9 included) surfaces as EOF/reset on the
proxy's reader thread -> the proxy marks itself dead -> the front's
pump reaps it exactly like `kill_decode_worker` — every accepted flight
is re-submitted typed and AT MOST ONCE through the survivors, a second
loss fails `WorkerLostError`. Sends run on a per-peer thread with a
bounded timeout, so one slow/hung decode host never blocks the front's
runtime thread (or the other peers' deliveries).
"""

from __future__ import annotations

import io
import json
import logging
import queue
import random
import select
import socket as socket_mod
import struct
import threading
import time
import zlib
from typing import Optional

import numpy as np

from genrec_tpu.disagg import chaosnet

from genrec_tpu.disagg.handoff import (
    HandoffRefusedError,
    KVHandoff,
    WorkerLostError,
    unpack_handoff,
)
from genrec_tpu.disagg.transport import SerializingTransport
from genrec_tpu.disagg.workers import DecodeWorker, Flight
from genrec_tpu.obs.spans import NULL_TRACER
from genrec_tpu.serving.kv_pool import KVPagePool, PagedConfig
from genrec_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from genrec_tpu.serving.types import Request, Response, ServingError

# Frame types (1 byte on the wire).
HELLO, HANDOFF, RESULT, REFUSED, STATS_REQ, STATS, SHUTDOWN, BYE = range(1, 9)

_LEN = struct.Struct(">Q")
_HDR = struct.Struct(">BI")

#: Hard ceiling on one frame — a corrupt length prefix must fail typed,
#: not allocate unbounded memory. Generous: the largest real frame is
#: one handoff's npz (pages_per_slot * page geometry).
MAX_FRAME_BYTES = 1 << 31


_CRC = struct.Struct(">I")


def send_frame(sock, ftype: int, meta: dict, payload: bytes = b"") -> int:
    """Write one length-prefixed, checksummed frame; returns bytes on
    the wire. The CRC32 covers header+meta+payload: TCP's 16-bit
    checksum misses real corruption at fleet scale, and a flipped bit
    in a RESULT's array payload would otherwise parse clean here and
    explode (or worse, mis-rank) far from the wire that caused it."""
    meta_b = json.dumps(meta).encode("utf-8")
    body = _HDR.pack(ftype, len(meta_b)) + meta_b + payload
    frame = _CRC.pack(zlib.crc32(body)) + body
    sock.sendall(_LEN.pack(len(frame)) + frame)
    return _LEN.size + len(frame)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock) -> tuple[int, dict, bytes]:
    """Read one frame. Raises ConnectionError on EOF/reset (peer death —
    mid-frame included: a kill -9 between the length prefix and the
    payload lands here, never as a truncated parse) AND on any corrupt
    framing — an insane length, a meta length past the frame end, or
    meta bytes that fail to decode. A flipped bit anywhere lands as the
    same typed error as a dead peer: the stream is presumed desynced
    and the connection unusable. The CRC32 check catches corruption
    ANYWHERE in the frame — payload bytes included — before a single
    field is trusted."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n < _CRC.size + _HDR.size or n > MAX_FRAME_BYTES:
        raise ConnectionError(f"insane frame length {n}")
    raw = _recv_exact(sock, n)
    (crc,) = _CRC.unpack_from(raw)
    frame = raw[_CRC.size:]
    if zlib.crc32(frame) != crc:
        raise ConnectionError(
            "corrupt frame: checksum mismatch (stream presumed desynced)"
        )
    ftype, meta_len = _HDR.unpack_from(frame)
    if meta_len > len(frame) - _HDR.size:
        raise ConnectionError(
            f"corrupt frame: meta length {meta_len} exceeds frame "
            f"body {len(frame) - _HDR.size}"
        )
    try:
        meta = json.loads(
            frame[_HDR.size:_HDR.size + meta_len].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ConnectionError(f"corrupt frame meta: {e}") from e
    if not isinstance(meta, dict):
        raise ConnectionError(
            f"corrupt frame meta: expected object, got "
            f"{type(meta).__name__}"
        )
    return ftype, meta, frame[_HDR.size + meta_len:]


def _jsonable(obj):
    """Recursively JSON-safe (numpy scalars/arrays -> python)."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


class SocketTransport(SerializingTransport):
    """The network tier: `send` is the serializing gather+pack (the wire
    bytes ARE the contract), and the bytes then leave the process on a
    `RemoteDecodeWorker`'s per-peer send thread instead of scattering
    into a local pool. Admission happens on the PEER — this transport's
    `admit` never runs in the front process (proxies own delivery), but
    the scatter path stays available so a host-side pool can reuse it.

    Carries the wire observability for the whole socket tier: the
    serializing counters (frames packed, wire bytes, serialize_ms) plus
    the network section proxies feed — receipts, connects/retries, peer
    losses, in-flight frames (gauge) and network_ms (send-side wall
    time per frame), so `transfer_ms` splits into serialize-vs-network
    in `stats()`/Prometheus."""

    name = "socket"

    def __init__(self):
        super().__init__()
        self.net_counters = {
            "receipts": 0,
            "connects": 0,
            "connect_retries": 0,
            "peer_losses": 0,
            "reconnects": 0,
            "heartbeat_misses": 0,
            "incarnation_discards": 0,
        }
        self.in_flight_frames = 0  # gauge: admitted, no receipt yet
        self.network_ms = LatencyHistogram()

    def stats(self) -> dict:
        out = super().stats()
        out["network"] = {
            **self.net_counters,
            "in_flight_frames": self.in_flight_frames,
            "network_ms": self.network_ms.summary(),
        }
        return out


class _RemotePoolStats:
    """Duck-typed `KVPagePool` stats surface for a remote peer: the
    front's aggregation (`stats()["kv_pool"]`, drain accounting) reads
    slots/seq_lens off worker pools — for a proxy those live across the
    wire, so this shim answers from the proxy's outstanding-flight
    ledger (exact: one outstanding seq == one bound remote slot)."""

    def __init__(self, proxy: "RemoteDecodeWorker", cfg: PagedConfig):
        self._proxy = proxy
        self.cfg = cfg
        self.scratch_page_count = 0

    @property
    def active_slot_count(self) -> int:
        return len(self._proxy._outstanding)

    @property
    def seq_lens(self) -> np.ndarray:
        out = np.zeros(self.cfg.max_slots, np.int32)
        for i, (_fl, n_tok, _t) in enumerate(
            list(self._proxy._outstanding.values())[: self.cfg.max_slots]
        ):
            out[i] = n_tok
        return out

    def release_scratch(self) -> int:
        return 0


class RemoteDecodeWorker:
    """The front-side proxy for one decode-host process.

    Duck-types the `DecodeWorker` surface the front schedules against
    (validate/admit/step/kill/stats/headroom/free_slots/idle), with:

    - ``validate`` checking the handoff against the peer's HANDSHAKE
      identity — params/catalog/layout/kv_dtype skew is refused typed on
      the front, before any bytes cross the wire (the host re-validates
      on receipt as the backstop);
    - ``admit`` enqueueing the frame to this peer's send thread and
      returning immediately — the front's runtime thread never blocks
      on a slow host, and slot accounting is the outstanding-seq ledger;
    - ``step`` draining receipts on the front's runtime thread (the
      single-writer discipline: futures resolve where every other
      worker's do);
    - reader/sender thread errors marking the proxy ``dead``, which the
      front's pump reaps exactly like an in-process worker kill.
    """

    role = "decode"
    owns_pool = False

    def __init__(self, addr: str, *, transport: SocketTransport, metrics,
                 counters: dict, flight_recorder, worker_id: str = "",
                 expected_head: Optional[str] = None,
                 replica_id: Optional[str] = None,
                 connect_timeout: float = 5.0,
                 connect_retries: int = 40,
                 hello_timeout: float = 600.0,
                 send_timeout: float = 60.0,
                 liveness_timeout: float = 60.0,
                 reconnect_max: int = 3,
                 reconnect_base: float = 0.05,
                 reconnect_cap: float = 2.0,
                 reconnect_seed: Optional[int] = None,
                 tracer=None, logger: Optional[logging.Logger] = None):
        self.peer_addr = addr
        self.transport = transport
        self.metrics = metrics
        self._counters = counters  # the FRONT's counter dict (shared)
        self._flight = flight_recorder
        self.worker_id = worker_id or f"remote:{addr}"
        self._expected_head = expected_head
        self.replica_id = replica_id
        self._connect_timeout = connect_timeout
        self._connect_retries = int(connect_retries)
        self._hello_timeout = hello_timeout
        self._send_timeout = send_timeout
        # Liveness deadline: a hung-but-connected peer (no frames at
        # all, despite the 0.25s STATS_REQ heartbeat soliciting them)
        # is treated as lost after this many silent seconds — distinct
        # from the send/recv timeouts, which only bound an ACTIVE
        # chunk. 0 disables the check.
        self._liveness_timeout = float(liveness_timeout)
        # Reconnect-with-backoff budget before the terminal peer-loss
        # path: reconnect_max attempts, exponential from reconnect_base
        # capped at reconnect_cap, each with seeded jitter in [0.5, 1)x.
        # reconnect_max=0 restores fail-fast (first error is terminal).
        self._reconnect_max = int(reconnect_max)
        self._reconnect_base = float(reconnect_base)
        self._reconnect_cap = float(reconnect_cap)
        self._jitter = random.Random(
            reconnect_seed if reconnect_seed is not None
            else (hash(addr) & 0xFFFFFFFF))
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._log = logger or logging.getLogger("genrec_tpu")
        self.dead = False
        self.draining = False
        self.identity: Optional[dict] = None
        self.head = None  # set by the front after the handshake
        self.params_step: Optional[int] = None
        self.warmup_compiles = 0
        self.admitted = 0
        self._seq = 0
        # seq -> (flight, n_tokens, t_enqueued): accepted, unresolved.
        # Runtime-thread writes only (admit/step/kill under the front's
        # runtime lock); the reader/sender threads never touch it.
        self._outstanding: dict[int, tuple] = {}
        self._inbox: queue.Queue = queue.Queue()
        self._send_q: queue.Queue = queue.Queue()
        self._sock: Optional[socket_mod.socket] = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._peer_stats: dict = {}
        self._stats_gen = 0
        self._stats_next = 0.0
        self.pool: Optional[_RemotePoolStats] = None
        # Connection epochs: every (re)connect bumps the incarnation,
        # I/O threads are born with theirs, and frames delivered by a
        # stale epoch's reader are DISCARDED in _dispatch — a RESULT
        # from before a reconnect can never resolve (or double-resolve)
        # a flight that was re-submitted after it.
        self.incarnation = 0
        self._reconnecting = False
        self._reconnect_lock = threading.Lock()
        # Set when an epoch dies with frames outstanding; the front's
        # pump drains take_stranded() on the runtime thread and
        # re-submits (at most once) through the prefill path.
        self._strand_pending = False
        # The in-flight connect socket of a reconnect attempt, so a
        # racing close() can abort it instead of leaking it.
        self._connecting_sock = None
        self._last_rx = time.monotonic()
        self._last_step = time.monotonic()
        # Most recent traced handoff: reconnect attempts record their
        # handoff_network spans against it (best-effort attribution —
        # retry wall-time shows on the critical path it stalled).
        self._last_handoff_trace = None

    # -- lifecycle -----------------------------------------------------------

    def warmup(self) -> None:
        """Connect + handshake (idempotent). The peer compiles its grid
        before accepting, so connect retries ride out host warmup; the
        HELLO read then waits on a generous timeout."""
        if self._sock is not None:
            return
        sock, meta = self._connect_once(
            retries=self._connect_retries,
            hello_timeout=self._hello_timeout,
        )
        self.identity = meta
        self.params_step = meta.get("params_step")
        self.warmup_compiles = int(meta.get("warmup_compiles", 0))
        self.pool = _RemotePoolStats(self, PagedConfig(
            max_slots=int(meta["max_slots"]),
            page_size=int(meta["page_size"]),
            pages_per_slot=int(meta["pages_per_slot"]),
            kv_dtype=str(meta.get("kv_dtype", "float32")),
        ))
        self._sock = sock
        self._last_rx = time.monotonic()
        self._start_io(sock)

    def _connect_once(self, *, retries: int,
                      hello_timeout: float) -> tuple:
        """One connect + HELLO handshake. Typed on every failure; the
        in-flight socket is tracked in `_connecting_sock` so a racing
        close() aborts it rather than leaking it."""
        host, _, port = self.peer_addr.rpartition(":")
        last_err: Optional[Exception] = None
        sock = None
        for attempt in range(retries + 1):
            if self._stop.is_set():
                raise WorkerLostError(
                    f"decode host {self.peer_addr}: proxy closing")
            try:
                sock = socket_mod.create_connection(
                    (host, int(port)), timeout=self._connect_timeout
                )
                break
            except OSError as e:
                last_err = e
                self.transport.net_counters["connect_retries"] += 1
                self._stop.wait(min(0.25 * (attempt + 1), 2.0))
        else:
            raise WorkerLostError(
                f"decode host {self.peer_addr} unreachable after "
                f"{retries} retries: {last_err}"
            )
        self._connecting_sock = sock
        if self._stop.is_set():
            sock.close()
            self._connecting_sock = None
            raise WorkerLostError(
                f"decode host {self.peer_addr}: proxy closing")
        self.transport.net_counters["connects"] += 1
        sock.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
        sock = chaosnet.maybe_wrap(sock, "front")
        self._connecting_sock = sock
        sock.settimeout(hello_timeout)
        try:
            ftype, meta, _ = recv_frame(sock)
        except (OSError, ConnectionError) as e:
            sock.close()
            self._connecting_sock = None
            raise WorkerLostError(
                f"decode host {self.peer_addr} died during handshake: {e}"
            ) from e
        if ftype != HELLO:
            sock.close()
            self._connecting_sock = None
            raise HandoffRefusedError(
                f"decode host {self.peer_addr} opened with frame type "
                f"{ftype}, expected HELLO"
            )
        if (self._expected_head is not None
                and meta.get("head") != self._expected_head):
            sock.close()
            self._connecting_sock = None
            raise HandoffRefusedError(
                f"decode host {self.peer_addr} serves head "
                f"{meta.get('head')!r}, this pool needs "
                f"{self._expected_head!r}"
            )
        sock.settimeout(self._send_timeout)
        self._connecting_sock = None
        return sock, meta

    def _start_io(self, sock) -> None:
        """Spawn this epoch's sender/reader pair, pinned to the current
        incarnation — a stale epoch's threads exit on their own when
        they notice the bump."""
        inc = self.incarnation
        self._threads = [t for t in self._threads if t.is_alive()]
        for fn, name in ((self._send_loop, "send"), (self._recv_loop, "recv")):
            t = threading.Thread(
                target=fn, args=(sock, inc),
                name=f"disagg-net-{name}-{self.peer_addr}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _ledger(self, operands_only: bool = False) -> None:
        """The peer budgets its own HBM (DecodeWorker._ledger in its
        process, refusing at ITS warmup); nothing is resident here."""

    def close(self, timeout: float = 5.0) -> None:
        """Graceful: ask the peer to drain and exit (and let the send
        thread actually flush the SHUTDOWN frame), then tear down the
        threads/socket. Safe to call twice."""
        if (self._sock is not None and not self.dead
                and not self._reconnecting):
            self._send_q.put((SHUTDOWN, {}, b"", None, self.incarnation))
            deadline = time.monotonic() + min(timeout, 2.0)
            while (not self._send_q.empty() and not self.dead
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            # Give the peer's BYE time to land (the recv thread exits on
            # it) so tearing the socket down never races its last write.
            for t in self._threads:
                if "recv" in t.name:
                    t.join(min(timeout, 5.0))
        self._shutdown(timeout)

    def _shutdown(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._send_q.put(None)  # unblock the sender
        # A close racing a reconnect: abort the attempt's in-flight
        # connect socket so the backoff thread can neither leak it nor
        # install it after this proxy is gone (it re-checks _stop under
        # _reconnect_lock before installing).
        cs = self._connecting_sock
        if cs is not None:
            try:
                cs.close()
            except OSError:
                pass
            self._connecting_sock = None
        for t in self._threads:
            t.join(timeout)
        self._threads = []
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    @property
    def sockets_closed(self) -> bool:
        return self._sock is None

    # -- scheduling surface (front runtime thread) ---------------------------

    @property
    def max_slots(self) -> int:
        return int(self.identity["max_slots"]) if self.identity else 0

    @property
    def free_slots(self) -> int:
        if self.dead or self._reconnecting:
            return 0
        return max(self.max_slots - len(self._outstanding), 0)

    @property
    def idle(self) -> bool:
        return not self._outstanding

    @property
    def reconnecting(self) -> bool:
        return self._reconnecting

    def occupancy(self) -> float:
        total = self.max_slots or 1
        return round(len(self._outstanding) / total, 4)

    def headroom(self) -> float:
        if self.dead or self.draining or self._reconnecting:
            return -1.0
        return round(self.free_slots / (self.max_slots or 1), 4)

    @property
    def recompilations(self) -> int:
        return int(self._peer_stats.get("recompilations", 0))

    @property
    def decode_steps(self) -> int:
        return int(self._peer_stats.get("decode_steps", 0))

    def validate(self, handoff: KVHandoff) -> None:
        """The admission contract, enforced on the SEND side against the
        peer's handshake identity: skew refuses typed before the frame
        is built, let alone sent. The host's own `DecodeWorker.validate`
        re-checks on receipt (REFUSED frame) as the backstop."""
        ident = self.identity
        if ident is None or self.dead:
            raise WorkerLostError(
                f"decode host {self.peer_addr} is not connected"
            )
        if handoff.head != ident["head"]:
            raise HandoffRefusedError(
                f"handoff for head {handoff.head!r} routed to remote "
                f"{ident['head']!r} decode host {self.peer_addr}"
            )
        if list(handoff.layout) != list(ident["layout"]):
            raise HandoffRefusedError(
                f"handoff KV layout {tuple(handoff.layout)} != decode "
                f"host {self.peer_addr}'s {tuple(ident['layout'])}"
            )
        if handoff.kv_dtype != ident.get("kv_dtype", "float32"):
            raise HandoffRefusedError(
                f"handoff KV pages are {handoff.kv_dtype} but decode "
                f"host {self.peer_addr} stores "
                f"{ident.get('kv_dtype')!r} — refusing to mix page "
                "storage dtypes across the wire"
            )
        if handoff.params_step != ident.get("params_step"):
            raise HandoffRefusedError(
                f"handoff prefilled at params step {handoff.params_step} "
                f"but decode host {self.peer_addr} serves step "
                f"{ident.get('params_step')} — refusing to mix params "
                "versions across the wire"
            )
        if handoff.catalog_version != ident.get("catalog_version"):
            raise HandoffRefusedError(
                f"handoff catalog {handoff.catalog_version} != decode "
                f"host {self.peer_addr}'s {ident.get('catalog_version')} "
                "— refusing to decode against a different corpus"
            )

    def admit(self, flight: Flight, handoff: KVHandoff) -> bool:
        """Accept one validated handoff for this peer: ledger the seq,
        hand the frame to the send thread, return. False when the peer's
        slots are all spoken for (the handoff stays pending at the
        front, same as a full local pool)."""
        if self.dead or self.free_slots == 0:
            return False
        wire = handoff.wire
        if wire is None:
            raise HandoffRefusedError(
                "socket transport needs serialized handoffs (no wire "
                "bytes on this one — was it sent through the in-process "
                "transport?)"
            )
        seq = self._seq
        self._seq += 1
        req = flight.req
        meta = {
            "seq": seq,
            "req": {
                "head": req.head,
                "history": np.asarray(req.history).tolist(),
                "user_id": int(req.user_id),
                "timestamps": (np.asarray(req.timestamps).tolist()
                               if req.timestamps is not None else None),
            },
        }
        self._outstanding[seq] = (flight, int(handoff.n_tokens),
                                  time.monotonic())
        self.transport.in_flight_frames += 1
        if flight.trace is not None:
            self._last_handoff_trace = flight.trace
        self._send_q.put((HANDOFF, meta, wire, flight.trace,
                          self.incarnation))
        self.transport.release(handoff)  # frame owns the bytes now
        self.admitted += 1
        self.metrics.record_admit(1)
        return True

    def step(self) -> bool:
        """Drain receipts on the front's runtime thread — RESULTs
        resolve futures, REFUSEDs fail them typed, STATS refresh the
        peer snapshot. Also keeps a low-rate STATS_REQ heartbeat going
        so peer counters stay fresh without a per-request round trip,
        and enforces the liveness deadline: a connected peer that has
        answered NOTHING (heartbeats included) for liveness_timeout
        seconds is hung, and hung means reconnect."""
        progressed = False
        while True:
            try:
                ftype, meta, payload, inc = self._inbox.get_nowait()
            except queue.Empty:
                break
            progressed |= self._dispatch(ftype, meta, payload, inc)
        now = time.monotonic()
        if now - self._last_step > 1.0:
            # The FRONT went quiet (nobody pumped this proxy), not the
            # peer — reset the rx clock instead of reading the gap as a
            # peer hang.
            self._last_rx = now
        self._last_step = now
        if (not self.dead and not self._reconnecting
                and self._sock is not None and now >= self._stats_next):
            self._stats_next = now + 0.25
            self._send_q.put((STATS_REQ, {}, b"", None, self.incarnation))
        if (self._liveness_timeout > 0 and not self.dead
                and not self._reconnecting and self._sock is not None
                and now - self._last_rx > self._liveness_timeout):
            silent = now - self._last_rx
            self.transport.net_counters["heartbeat_misses"] += 1
            self._flight.record(
                "peer_hung", peer=self.peer_addr, worker=self.worker_id,
                silent_s=round(silent, 3),
                outstanding=len(self._outstanding),
            )
            self._log.warning(
                f"disagg: decode host {self.peer_addr} hung — no frames "
                f"for {silent:.1f}s (liveness deadline "
                f"{self._liveness_timeout}s) with "
                f"{len(self._outstanding)} outstanding"
            )
            self._begin_reconnect(
                "liveness",
                TimeoutError(
                    f"no frames from {self.peer_addr} in {silent:.1f}s"),
                self.incarnation,
            )
        return progressed

    def take_stranded(self) -> list[Flight]:
        """Runtime thread: collect the flights whose connection epoch
        died under them (their KV pages are unreachable behind the old
        connection — the host orphans them on disconnect). The front's
        pump re-submits each through the prefill path, riding the same
        at-most-once ledger as a worker death."""
        if not self._strand_pending:
            return []
        self._strand_pending = False
        stranded = [fl for (fl, _n, _t) in self._outstanding.values()
                    if not fl.fut.done()]
        self.transport.in_flight_frames = max(
            0, self.transport.in_flight_frames - len(self._outstanding))
        self._outstanding.clear()
        return stranded

    def _dispatch(self, ftype: int, meta: dict, payload: bytes,
                  inc: Optional[int] = None) -> bool:
        if inc is not None and inc != self.incarnation:
            # A stale epoch's reader delivered this after the reconnect
            # bumped the incarnation: the flight it answers was already
            # stranded and re-submitted, so resolving from it would be
            # a double-finalize. Discard, counted.
            if ftype in (RESULT, REFUSED):
                self.transport.net_counters["incarnation_discards"] += 1
                self._log.info(
                    f"disagg: discarding stale incarnation-{inc} frame "
                    f"(type {ftype}, seq {meta.get('seq')}) from "
                    f"{self.peer_addr} (now incarnation "
                    f"{self.incarnation})"
                )
            return False
        if ftype == STATS:
            self._peer_stats = meta
            self._stats_gen += 1
            return False
        if ftype == BYE:
            return False
        if ftype not in (RESULT, REFUSED):
            self._log.warning(
                f"disagg: unexpected frame type {ftype} from "
                f"{self.peer_addr}"
            )
            return False
        ent = self._outstanding.pop(meta.get("seq"), None)
        if ent is None:
            return False
        self.transport.in_flight_frames = max(
            0, self.transport.in_flight_frames - 1)
        self.transport.net_counters["receipts"] += 1
        flight, _n_tok, _t = ent
        if ftype == REFUSED:
            err_cls = (HandoffRefusedError
                       if meta.get("etype") == "HandoffRefusedError"
                       else ServingError)
            self._counters["handoffs_refused"] += 1
            self._flight.record(
                "handoff_refused", peer=self.peer_addr,
                worker=self.worker_id, reason=meta.get("error", ""),
            )
            if not flight.fut.done():
                flight.fut.set_exception(err_cls(
                    f"decode host {self.peer_addr} refused: "
                    f"{meta.get('error', '')}"
                ))
                self.metrics.record_failure(1)
            return True
        with np.load(io.BytesIO(payload)) as z:
            items = np.array(z["items"])
            scores = np.array(z["scores"])
            sem_ids = np.array(z["sem_ids"]) if "sem_ids" in z.files else None
        resp = Response(
            head=meta["head"], items=items, scores=scores, sem_ids=sem_ids,
            params_step=meta.get("params_step"),
            bucket=tuple(meta["bucket"]),
            queue_wait_s=float(meta.get("queue_wait_s", 0.0)),
            compute_s=float(meta.get("compute_s", 0.0)),
            total_s=time.monotonic() - flight.t_enq,
            catalog_version=meta.get("catalog_version"),
            request_id=(flight.trace.trace_id
                        if flight.trace is not None else None),
            replica_id=self.replica_id,
            prefill_worker_id=meta.get("prefill_worker_id"),
            decode_worker_id=meta.get("decode_worker_id", self.worker_id),
        )
        if not flight.fut.done():
            flight.fut.set_result(resp)
        self.metrics.record_response(
            resp.queue_wait_s, resp.compute_s, resp.total_s, head=resp.head
        )
        self.metrics.record_evict(1)
        return True

    def refresh_stats(self, timeout: float = 5.0) -> dict:
        """Round-trip a STATS_REQ (drain/CI path: the final "0 recompiles
        / pools clean / sockets closed" reads must be FRESH, not the
        heartbeat's last sample). Caller must be the scheduling thread."""
        if self.dead or self._reconnecting or self._sock is None:
            return dict(self._peer_stats)
        gen = self._stats_gen
        self._send_q.put((STATS_REQ, {}, b"", None, self.incarnation))
        deadline = time.monotonic() + timeout
        while (self._stats_gen == gen and not self.dead
               and time.monotonic() < deadline):
            self.step()
            time.sleep(0.005)
        return dict(self._peer_stats)

    # -- failure -------------------------------------------------------------

    def _on_peer_lost(self, where: str, err: Exception) -> None:
        if self.dead:
            return
        self.dead = True
        self.transport.net_counters["peer_losses"] += 1
        self._flight.record(
            "disagg_peer_lost", peer=self.peer_addr, worker=self.worker_id,
            where=where, error=str(err),
            outstanding=len(self._outstanding),
        )
        self._log.warning(
            f"disagg: decode host {self.peer_addr} lost ({where}: {err}) "
            f"with {len(self._outstanding)} frames outstanding"
        )

    # -- self-healing --------------------------------------------------------

    def _begin_reconnect(self, where: str, err: Exception,
                         inc: int) -> None:
        """First stop on any connection error: open a new epoch and try
        to get the peer back before declaring it dead. Idempotent per
        epoch — send thread, recv thread and the liveness check can all
        report the same loss; exactly one wins."""
        with self._reconnect_lock:
            if (self.dead or self._stop.is_set() or self._reconnecting
                    or inc != self.incarnation):
                return
            if self._reconnect_max <= 0:
                self._on_peer_lost(where, err)
                return
            self._reconnecting = True
            self.incarnation += 1
            self._strand_pending = True
            # Fresh epoch, fresh send queue: the dying epoch's sender
            # must never pick up a frame admitted for the new one and
            # push it down its own (dead) socket — that frame would be
            # silently lost with its flight still ledgered, and the
            # caller would hang to its timeout.
            self._send_q = queue.Queue()
        self._log.warning(
            f"disagg: decode host {self.peer_addr} connection lost "
            f"({where}: {err}) — reconnecting (incarnation "
            f"{self.incarnation}, budget {self._reconnect_max})"
        )
        t = threading.Thread(
            target=self._reconnect_loop, args=(where, err),
            name=f"disagg-net-reconnect-{self.peer_addr}", daemon=True,
        )
        t.start()
        self._threads.append(t)

    def _same_identity(self, meta: dict) -> bool:
        ident = self.identity or {}
        return all(
            meta.get(k) == ident.get(k)
            for k in ("head", "layout", "kv_dtype", "params_step",
                      "catalog_version")
        )

    def _record_reconnect_span(self, ctx, t0: float, attempt: int,
                               ok: bool) -> None:
        if ctx is None or not self.tracer.enabled:
            return
        self.tracer.record_span(
            "handoff_network", ctx.trace_id, t0, time.monotonic(),
            parent_id=ctx.parent_span_id, side="reconnect",
            attempt=attempt, ok=ok, peer=self.peer_addr,
            component="disagg_front", worker=self.worker_id,
        )

    def _reconnect_loop(self, where: str, err: Exception) -> None:
        old, self._sock = self._sock, None
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        last_err: Exception = err
        ctx = self._last_handoff_trace
        for attempt in range(self._reconnect_max):
            delay = min(self._reconnect_cap,
                        self._reconnect_base * (2 ** attempt))
            delay *= 0.5 + 0.5 * self._jitter.random()
            if self._stop.wait(delay):
                self._reconnecting = False
                return  # closing: close() owns the teardown, no loss event
            t0 = time.monotonic()
            try:
                sock, meta = self._connect_once(
                    retries=0,
                    hello_timeout=min(self._hello_timeout, 30.0),
                )
            except (WorkerLostError, HandoffRefusedError, OSError,
                    ConnectionError) as e:
                last_err = e
                self._record_reconnect_span(ctx, t0, attempt, ok=False)
                if self._stop.is_set():
                    self._reconnecting = False
                    return
                continue
            if not self._same_identity(meta):
                sock.close()
                self._reconnecting = False
                self._on_peer_lost("reconnect", HandoffRefusedError(
                    f"decode host {self.peer_addr} came back with a "
                    f"different identity (params/catalog/layout) — "
                    "refusing to resume against it"
                ))
                return
            self._record_reconnect_span(ctx, t0, attempt, ok=True)
            with self._reconnect_lock:
                if self._stop.is_set():
                    sock.close()
                    self._reconnecting = False
                    return
                self._sock = sock
                self._last_rx = time.monotonic()
                self._reconnecting = False
            self.transport.net_counters["reconnects"] += 1
            self._flight.record(
                "peer_reconnected", peer=self.peer_addr,
                worker=self.worker_id, attempts=attempt + 1,
                incarnation=self.incarnation, where=where,
            )
            self._log.warning(
                f"disagg: decode host {self.peer_addr} reconnected "
                f"(attempt {attempt + 1}, incarnation {self.incarnation})"
            )
            self._start_io(sock)
            return
        # Budget exhausted: the existing terminal path (front reaps the
        # dead proxy; anything still outstanding re-submits typed).
        self._reconnecting = False
        self._on_peer_lost(where, last_err)

    def kill(self) -> list[Flight]:
        """Reap: every accepted-unresolved flight is stranded (its KV
        lives in the dead process). The front re-submits each typed,
        at most once — `DecodeWorker.kill`'s contract, across the wire."""
        self.dead = True
        self._strand_pending = False
        stranded = []
        for seq, (flight, _n, _t) in list(self._outstanding.items()):
            if not flight.fut.done():
                stranded.append(flight)
        self.transport.in_flight_frames = max(
            0, self.transport.in_flight_frames - len(self._outstanding))
        self._outstanding.clear()
        self._shutdown()
        return stranded

    # -- I/O threads ---------------------------------------------------------

    def _send_loop(self, sock, inc: int) -> None:
        # This epoch's queue, captured at entry: a reconnect swaps in a
        # fresh queue for the new epoch, so frames admitted after the
        # swap can never be consumed here and pushed down THIS (dead)
        # socket — the silent-loss race the chaos bench caught.
        q = self._send_q
        while not self._stop.is_set() and inc == self.incarnation:
            try:
                item = q.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                break
            ftype, meta, payload, trace, item_inc = item
            if item_inc != inc:
                if item_inc > inc:
                    # Admit raced the queue swap and landed a new-epoch
                    # frame in this epoch's queue: hand it to the live
                    # sender instead of the dead socket.
                    self._send_q.put(item)
                    break
                # Queued before a reconnect: its flight was stranded and
                # re-submitted through prefill — sending the stale frame
                # would make the host decode work nobody can claim.
                continue
            t0 = time.monotonic()
            try:
                nbytes = send_frame(sock, ftype, meta, payload)
            except (OSError, ConnectionError) as e:
                self._begin_reconnect("send", e, inc)
                break
            t1 = time.monotonic()
            if ftype == HANDOFF:
                self.transport.network_ms.record(t1 - t0)
                if trace is not None and self.tracer.enabled:
                    # The network hop as its own critical-path segment
                    # (scripts/trace_report.py SEGMENT_OF), attributed
                    # to the peer that received it.
                    self.tracer.record_span(
                        "handoff_network", trace.trace_id, t0, t1,
                        parent_id=trace.parent_span_id, side="send",
                        peer=self.peer_addr, transfer_bytes=nbytes,
                        component="disagg_front", worker=self.worker_id,
                    )
        # Exiting (incarnation bump, stop, or error): frames meant for a
        # NEWER epoch must survive this epoch's death — forward them.
        leftovers = []
        while True:
            try:
                item = q.get_nowait()
            except queue.Empty:
                break
            if item is not None and item[4] > inc:
                leftovers.append(item)
        for item in leftovers:
            self._send_q.put(item)
            t1 = time.monotonic()
            if ftype == HANDOFF:
                self.transport.network_ms.record(t1 - t0)
                if trace is not None and self.tracer.enabled:
                    # The network hop as its own critical-path segment
                    # (scripts/trace_report.py SEGMENT_OF), attributed
                    # to the peer that received it.
                    self.tracer.record_span(
                        "handoff_network", trace.trace_id, t0, t1,
                        parent_id=trace.parent_span_id, side="send",
                        peer=self.peer_addr, transfer_bytes=nbytes,
                        component="disagg_front", worker=self.worker_id,
                    )

    def _recv_loop(self, sock, inc: int) -> None:
        # select-gated: the blocking read only STARTS once bytes exist,
        # so the socket's timeout bounds per-chunk stalls mid-frame (a
        # genuine peer hang) without a between-frames idle timeout ever
        # firing mid-read and desyncing the stream.
        while not self._stop.is_set() and inc == self.incarnation:
            try:
                readable, _, _ = select.select([sock], [], [], 0.05)
            except (OSError, ValueError):
                break
            if not readable:
                continue
            try:
                frame = recv_frame(sock)
            except (OSError, ConnectionError, ValueError) as e:
                if not self._stop.is_set():
                    self._begin_reconnect("recv", e, inc)
                break
            self._last_rx = time.monotonic()
            self._inbox.put((frame[0], frame[1], frame[2], inc))
            if frame[0] == BYE:
                break  # graceful close: the EOF behind it is not a loss

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        peer = dict(self._peer_stats)
        return {
            "peer_addr": self.peer_addr,
            "slots_active": len(self._outstanding),
            "slots_total": self.max_slots,
            "occupancy": self.occupancy(),
            "headroom": self.headroom(),
            "admitted": self.admitted,
            "decode_steps": self.decode_steps,
            "in_flight_frames": len(self._outstanding),
            "warmup_compiles": self.warmup_compiles,
            "recompilations": self.recompilations,
            "incarnation": self.incarnation,
            "reconnecting": self._reconnecting,
            "peer": peer,
        }


# ---------------------------------------------------------------------------
# The decode-host process
# ---------------------------------------------------------------------------


def _resolve_factory(spec: str):
    """``module:function`` or ``/path/to/file.py:function`` -> callable.
    The factory runs in the CHILD process and must rebuild the exact
    head/params the front serves (same seed/config), so both sides of
    the wire agree on identity — the handshake and per-handoff
    validation then PROVE it rather than assume it."""
    mod_spec, _, fn_name = spec.rpartition(":")
    if not mod_spec or not fn_name:
        raise ValueError(f"factory spec {spec!r} is not 'module:function'")
    if mod_spec.endswith(".py"):
        import importlib.util

        m_spec = importlib.util.spec_from_file_location(
            "_genrec_decode_factory", mod_spec)
        module = importlib.util.module_from_spec(m_spec)
        m_spec.loader.exec_module(module)
    else:
        import importlib

        module = importlib.import_module(mod_spec)
    return getattr(module, fn_name)


class _HostFlights:
    """One connection's in-flight ledger: seq -> Flight, plus the
    pending deque for handoffs that validated but found no free slot
    (retried every loop pass — the front's pending semantics,
    host-side). Per-CONNECTION because each front numbers its seqs from
    zero: two fronts' seq spaces must never collide in one dict."""

    def __init__(self):
        self.flights: dict[int, Flight] = {}
        self.pending: list[tuple[int, Flight, KVHandoff]] = []


class _HostConn:
    """One accepted front connection: its socket, its seq ledger, and
    its own drain state (a SHUTDOWN drains THIS front's flights; other
    fronts keep serving)."""

    __slots__ = ("sock", "peer", "cid", "ledger", "draining")

    def __init__(self, sock, peer, cid: int):
        self.sock = sock
        self.peer = peer
        self.cid = cid
        self.ledger = _HostFlights()
        self.draining = False


def serve_decode_host(factory: str, *, host: str = "127.0.0.1",
                      port: int = 0, worker_id: str = "remote-d0",
                      announce=None, idle_timeout: Optional[float] = None,
                      persist: bool = False,
                      logger: Optional[logging.Logger] = None) -> dict:
    """Run one decode worker as a network peer (the child-process
    entrypoint behind ``python -m genrec_tpu.disagg.net``).

    Binds + announces the port FIRST (``GENREC_DECODE_PORT <n>`` on
    stdout — `spawn_decode_host` reads it), then builds and warms the
    real `DecodeWorker` from the factory, then serves an ACCEPT LOOP:
    the warmed worker/pool outlive any one front, so the host survives
    a front disconnect, accepts its reconnect, and serves several
    fronts concurrently (each connection gets its own HELLO and its own
    seq ledger). An abruptly-dropped front's resident flights are
    orphaned — they finish decoding and free their slots, their results
    discarded (the front re-submits through prefill on its side).

    Exits after the LAST connected front completes a graceful SHUTDOWN
    (drain + final STATS + BYE); with ``persist=True`` it instead keeps
    listening until the process is signalled — the long-lived standby /
    multi-front mode. Returns the final stats dict (useful when called
    in-process by tests)."""
    log = logger or logging.getLogger("genrec_tpu")
    from genrec_tpu.core import chaos as chaos_mod

    # A spawned host installs its network-fault schedule from the env
    # (it cannot enter the parent's `chaos.inject` block).
    chaos_mod.install_net_plan_from_env()
    srv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    srv.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(8)
    bound_port = srv.getsockname()[1]
    import sys

    out = announce if announce is not None else sys.stdout
    print(f"GENREC_DECODE_PORT {bound_port}", file=out, flush=True)

    cfg = _resolve_factory(factory)()
    head = cfg["head"]
    params = cfg["params"]
    head.on_params(params)
    mesh = None
    if cfg.get("mesh_shape"):
        from genrec_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(dict(cfg["mesh_shape"]))
    paged: PagedConfig = cfg["paged_config"]
    n_layers, n_heads, head_dim, dtype = head.paged_layout()
    pool = KVPagePool(paged, n_layers, n_heads, head_dim, dtype)
    transport = SerializingTransport()
    from genrec_tpu.obs.flight_recorder import get_flight_recorder

    worker = DecodeWorker(
        worker_id, head, params, transport=transport, pool=pool,
        owns_pool=True, ladder=cfg["ladder"], metrics=ServingMetrics(),
        flight_recorder=get_flight_recorder().scoped(
            "decode_host", worker_id=worker_id),
        params_step=cfg.get("params_step"),
        hbm_budget_bytes=cfg.get("hbm_budget_bytes"),
        mesh=mesh, model_axis=cfg.get("model_axis", "model"),
        logger=log,
    )
    worker._ledger(operands_only=True)
    worker.warmup()
    from genrec_tpu.disagg.handoff import layout_of

    hello = {
        "worker_id": worker_id,
        "head": head.name,
        "layout": list(layout_of(head)),
        "kv_dtype": paged.kv_dtype,
        "params_step": cfg.get("params_step"),
        "catalog_version": head.catalog_version,
        "max_slots": paged.max_slots,
        "page_size": paged.page_size,
        "pages_per_slot": paged.pages_per_slot,
        "warmup_compiles": worker.warmup_compiles,
        "tp_devices": int(mesh.size) if mesh is not None else 1,
    }
    srv.settimeout(idle_timeout)
    try:
        conn0, peer0 = srv.accept()
    except socket_mod.timeout:
        srv.close()
        raise TimeoutError("no front connected before idle_timeout")
    srv.settimeout(None)

    conns: dict[int, _HostConn] = {}
    next_cid = [0]
    # Flights whose front dropped without a SHUTDOWN: they finish
    # decoding (freeing their slots/pages), their results discarded.
    orphans: list[Flight] = []

    def _attach(raw, peer) -> None:
        raw.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
        sock = chaosnet.maybe_wrap(raw, "host")
        sock.settimeout(60.0)  # per-chunk bound once a frame has started
        try:
            send_frame(sock, HELLO, hello)
        except (OSError, ConnectionError):
            try:
                sock.close()
            except OSError:
                pass
            return
        c = _HostConn(sock, peer, next_cid[0])
        next_cid[0] += 1
        conns[c.cid] = c
        log.info(
            f"disagg host {worker_id}: serving {head.name} to {peer} "
            f"(conn {c.cid})"
        )

    def _drop(c: _HostConn, why: str) -> None:
        """Abrupt loss of one front: orphan its resident flights, free
        its never-admitted pending handoffs, close the socket — and
        keep serving everyone else."""
        if c.cid not in conns:
            return
        del conns[c.cid]
        for fl in c.ledger.flights.values():
            orphans.append(fl)
        for _seq, _fl, h in c.ledger.pending:
            transport.release(h)
        try:
            c.sock.close()
        except OSError:
            pass
        log.warning(
            f"disagg host {worker_id}: front (conn {c.cid}) dropped "
            f"({why}) — {len(c.ledger.flights)} flights orphaned, "
            f"serving {len(conns)} remaining front(s)"
        )

    def _host_stats(draining: bool) -> dict:
        return _jsonable({
            **worker.stats(),
            "pool": {
                "pages_in_use": pool.allocator.pages_in_use,
                "pages_free": pool.allocator.pages_free,
                "slots_active": pool.active_slot_count,
                "kv_tokens_resident": int(pool.seq_lens.sum()),
            },
            "transport": transport.stats(),
            "pending": sum(len(c.ledger.pending) for c in conns.values()),
            "in_flight": (sum(len(c.ledger.flights)
                              for c in conns.values()) + len(orphans)),
            "fronts": len(conns),
            "orphaned": len(orphans),
            "draining": draining,
        })

    def _try_admit(c: _HostConn, seq: int, fl: Flight,
                   h: KVHandoff) -> bool:
        try:
            worker.validate(h)
            ok = worker.admit(fl, h)
        except Exception as e:  # noqa: BLE001 — refuse THIS seq typed
            transport.release(h)
            send_frame(c.sock, REFUSED, {
                "seq": seq, "error": str(e),
                "etype": type(e).__name__,
            })
            return True
        if not ok:
            return False
        c.ledger.flights[seq] = fl
        return True

    def _handle_frame(c: _HostConn, ftype: int, meta: dict,
                      payload: bytes) -> None:
        if ftype == HANDOFF:
            h, _k, _v = unpack_handoff(payload)
            r = meta["req"]
            req = Request(
                head=r["head"],
                history=np.asarray(r["history"], np.int64),
                user_id=int(r["user_id"]),
                timestamps=(np.asarray(r["timestamps"])
                            if r.get("timestamps") is not None
                            else None),
                trace=h.trace,
            )
            fl = Flight(req)
            if not _try_admit(c, meta["seq"], fl, h):
                c.ledger.pending.append((meta["seq"], fl, h))
        elif ftype == STATS_REQ:
            send_frame(c.sock, STATS, _host_stats(c.draining))
        elif ftype == SHUTDOWN:
            c.draining = True

    def _ship_receipts(c: _HostConn) -> None:
        for seq, fl in list(c.ledger.flights.items()):
            if not fl.fut.done():
                continue
            del c.ledger.flights[seq]
            exc = fl.fut.exception()
            if exc is not None:
                send_frame(c.sock, REFUSED, {
                    "seq": seq, "error": str(exc),
                    "etype": type(exc).__name__,
                })
                continue
            resp = fl.fut.result()
            buf = io.BytesIO()
            arrays = {"items": np.asarray(resp.items),
                      "scores": np.asarray(resp.scores)}
            if resp.sem_ids is not None:
                arrays["sem_ids"] = np.asarray(resp.sem_ids)
            np.savez(buf, **arrays)
            send_frame(c.sock, RESULT, {
                "seq": seq,
                "head": resp.head,
                "params_step": resp.params_step,
                "catalog_version": resp.catalog_version,
                "bucket": list(resp.bucket),
                "queue_wait_s": resp.queue_wait_s,
                "compute_s": resp.compute_s,
                "prefill_worker_id": resp.prefill_worker_id,
                "decode_worker_id": worker_id,
            }, buf.getvalue())

    final_stats: dict = {}
    exiting = False
    try:
        _attach(conn0, peer0)
        while True:
            busy = bool(orphans) or any(
                c.ledger.flights or c.ledger.pending
                for c in conns.values()
            )
            by_sock = {c.sock: c for c in conns.values()}
            # select-gated read: never start a blocking frame read on an
            # idle wire (a poll timeout mid-frame would desync it).
            try:
                readable, _, _ = select.select(
                    [srv, *by_sock], [], [], 0.0005 if busy else 0.05)
            except (OSError, ValueError):
                readable = []
            for r in readable:
                if r is srv:
                    try:
                        raw, peer = srv.accept()
                    except OSError:
                        continue
                    _attach(raw, peer)
                    continue
                c = by_sock[r]
                try:
                    ftype, meta, payload = recv_frame(r)
                    _handle_frame(c, ftype, meta, payload)
                except (OSError, ConnectionError, ValueError) as e:
                    _drop(c, str(e))
            # Pending handoffs retry as slots free up (front semantics).
            for c in list(conns.values()):
                still = []
                send_err = None
                for seq, fl, h in c.ledger.pending:
                    try:
                        if not _try_admit(c, seq, fl, h):
                            still.append((seq, fl, h))
                    except (OSError, ConnectionError) as e:
                        send_err = e
                        transport.release(h)
                c.ledger.pending = still
                if send_err is not None:
                    _drop(c, str(send_err))
            worker.step()
            orphans = [fl for fl in orphans if not fl.fut.done()]
            # Ship every finished flight's receipt to its OWN front.
            for c in list(conns.values()):
                try:
                    _ship_receipts(c)
                except (OSError, ConnectionError) as e:
                    _drop(c, str(e))
                    continue
                if (c.draining and not c.ledger.flights
                        and not c.ledger.pending):
                    last = len(conns) == 1 and not persist
                    if last and orphans:
                        continue  # orphans still hold slots: drain them
                    if last:
                        pool.release_scratch()
                        stats_out = final_stats = _host_stats(True)
                    else:
                        stats_out = _host_stats(True)
                    try:
                        send_frame(c.sock, STATS, stats_out)
                        send_frame(c.sock, BYE, {})
                    except (OSError, ConnectionError):
                        pass
                    del conns[c.cid]
                    try:
                        c.sock.close()
                    except OSError:
                        pass
                    log.info(
                        f"disagg host {worker_id}: front (conn {c.cid}) "
                        "drained and closed"
                    )
                    if last:
                        exiting = True
            if exiting and not conns:
                break
    finally:
        for c in list(conns.values()):
            try:
                c.sock.close()
            except OSError:
                pass
        srv.close()
    log.info(f"disagg host {worker_id}: drained, exiting")
    return final_stats


def spawn_decode_host(factory: str, *, host: str = "127.0.0.1",
                      worker_id: str = "remote-d0",
                      env: Optional[dict] = None,
                      persist: bool = False,
                      startup_timeout: float = 120.0):
    """Launch `serve_decode_host` in a fresh OS process and return
    ``(Popen, "host:port")`` once the child announces its port. ``env``
    overlays os.environ — the caller pins JAX_PLATFORMS/XLA_FLAGS there
    (they must be set before the child imports jax, which is exactly
    what a fresh process guarantees)."""
    import os
    import subprocess
    import sys

    cfg = {"factory": factory, "host": host, "port": 0,
           "worker_id": worker_id, "persist": persist}
    full_env = dict(os.environ)
    full_env.update(env or {})
    # The child must resolve genrec_tpu the same way the parent did.
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    full_env["PYTHONPATH"] = (
        repo + os.pathsep + full_env.get("PYTHONPATH", "")
    )
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import json, sys\n"
         "from genrec_tpu.disagg.net import serve_decode_host\n"
         "serve_decode_host(**json.loads(sys.argv[1]))",
         json.dumps(cfg)],
        stdout=subprocess.PIPE, env=full_env, text=True, bufsize=1,
    )
    deadline = time.monotonic() + startup_timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"decode host {worker_id} exited rc={proc.returncode} "
                "before announcing its port"
            )
        line = proc.stdout.readline()
        if line.startswith("GENREC_DECODE_PORT "):
            return proc, f"{host}:{int(line.split()[1])}"
    proc.kill()
    raise TimeoutError(
        f"decode host {worker_id} did not announce a port within "
        f"{startup_timeout}s"
    )


if __name__ == "__main__":
    import sys

    serve_decode_host(**json.loads(sys.argv[1]))
