"""Cross-host serving: the socket `KVTransport` tier + the decode-host
process runtime.

The serializing transport already pinned the cross-host CONTRACT — every
handoff round-trips through `pack_handoff` bytes — but both roles still
shared one process, one GIL and one device. This module makes the hop
real: prefill stays on the front (with the front's control plane —
submit/route/SLO/autoscaler — untouched), decode workers run in their
OWN OS processes (`serve_decode_host`), and the handoff bytes cross a
TCP socket instead of a function call. Roles genuinely overlap: the
front prefills the next batch while the decode hosts step their slots.

Wire framing (everything on the socket is one of these):

    [8B big-endian frame length][1B type][4B meta length][meta JSON][payload]

- ``HELLO``    (host -> front): the peer's full serving identity —
  worker_id, head, KV layout, kv_dtype, params_step, catalog_version,
  pool geometry, warmup_compiles. The front's proxy validates every
  handoff against THIS, so skew is refused typed before a byte of page
  content crosses the wire.
- ``HANDOFF``  (front -> host): meta carries the request (history /
  user_id — the decode side finalizes against the request) + a
  monotonic ``seq``; payload is the `pack_handoff` bytes, verbatim.
- ``RESULT``   (host -> front): meta is the response provenance
  (bucket, timings, worker ids), payload an ``.npz`` of
  items/scores/sem_ids — bit-exact arrays, not reprinted floats.
- ``REFUSED``  (host -> front): typed failure for one seq
  (HandoffRefusedError backstop, finalize errors) — never silence.
- ``STATS_REQ``/``STATS``: the peer's stats()/pool/recompilation
  counters, so "0 steady-state recompiles" and "pools clean after
  drain" stay checkable ACROSS the process boundary.
- ``SHUTDOWN``/``BYE``: graceful drain handshake; the host exits after
  BYE and the front knows the socket closed clean.

Failure semantics (the disagg contract, held across processes): a peer
that dies mid-frame (kill -9 included) surfaces as EOF/reset on the
proxy's reader thread -> the proxy marks itself dead -> the front's
pump reaps it exactly like `kill_decode_worker` — every accepted flight
is re-submitted typed and AT MOST ONCE through the survivors, a second
loss fails `WorkerLostError`. Sends run on a per-peer thread with a
bounded timeout, so one slow/hung decode host never blocks the front's
runtime thread (or the other peers' deliveries).
"""

from __future__ import annotations

import io
import json
import logging
import queue
import select
import socket as socket_mod
import struct
import threading
import time
from typing import Optional

import numpy as np

from genrec_tpu.disagg.handoff import (
    HandoffRefusedError,
    KVHandoff,
    WorkerLostError,
    unpack_handoff,
)
from genrec_tpu.disagg.transport import SerializingTransport
from genrec_tpu.disagg.workers import DecodeWorker, Flight
from genrec_tpu.obs.spans import NULL_TRACER
from genrec_tpu.serving.kv_pool import KVPagePool, PagedConfig
from genrec_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from genrec_tpu.serving.types import Request, Response, ServingError

# Frame types (1 byte on the wire).
HELLO, HANDOFF, RESULT, REFUSED, STATS_REQ, STATS, SHUTDOWN, BYE = range(1, 9)

_LEN = struct.Struct(">Q")
_HDR = struct.Struct(">BI")

#: Hard ceiling on one frame — a corrupt length prefix must fail typed,
#: not allocate unbounded memory. Generous: the largest real frame is
#: one handoff's npz (pages_per_slot * page geometry).
MAX_FRAME_BYTES = 1 << 31


def send_frame(sock, ftype: int, meta: dict, payload: bytes = b"") -> int:
    """Write one length-prefixed frame; returns bytes on the wire."""
    meta_b = json.dumps(meta).encode("utf-8")
    frame = _HDR.pack(ftype, len(meta_b)) + meta_b + payload
    sock.sendall(_LEN.pack(len(frame)) + frame)
    return _LEN.size + len(frame)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock) -> tuple[int, dict, bytes]:
    """Read one frame. Raises ConnectionError on EOF/reset (peer death —
    mid-frame included: a kill -9 between the length prefix and the
    payload lands here, never as a truncated parse)."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n < _HDR.size or n > MAX_FRAME_BYTES:
        raise ConnectionError(f"insane frame length {n}")
    frame = _recv_exact(sock, n)
    ftype, meta_len = _HDR.unpack_from(frame)
    meta = json.loads(frame[_HDR.size:_HDR.size + meta_len].decode("utf-8"))
    return ftype, meta, frame[_HDR.size + meta_len:]


def _jsonable(obj):
    """Recursively JSON-safe (numpy scalars/arrays -> python)."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


class SocketTransport(SerializingTransport):
    """The network tier: `send` is the serializing gather+pack (the wire
    bytes ARE the contract), and the bytes then leave the process on a
    `RemoteDecodeWorker`'s per-peer send thread instead of scattering
    into a local pool. Admission happens on the PEER — this transport's
    `admit` never runs in the front process (proxies own delivery), but
    the scatter path stays available so a host-side pool can reuse it.

    Carries the wire observability for the whole socket tier: the
    serializing counters (frames packed, wire bytes, serialize_ms) plus
    the network section proxies feed — receipts, connects/retries, peer
    losses, in-flight frames (gauge) and network_ms (send-side wall
    time per frame), so `transfer_ms` splits into serialize-vs-network
    in `stats()`/Prometheus."""

    name = "socket"

    def __init__(self):
        super().__init__()
        self.net_counters = {
            "receipts": 0,
            "connects": 0,
            "connect_retries": 0,
            "peer_losses": 0,
        }
        self.in_flight_frames = 0  # gauge: admitted, no receipt yet
        self.network_ms = LatencyHistogram()

    def stats(self) -> dict:
        out = super().stats()
        out["network"] = {
            **self.net_counters,
            "in_flight_frames": self.in_flight_frames,
            "network_ms": self.network_ms.summary(),
        }
        return out


class _RemotePoolStats:
    """Duck-typed `KVPagePool` stats surface for a remote peer: the
    front's aggregation (`stats()["kv_pool"]`, drain accounting) reads
    slots/seq_lens off worker pools — for a proxy those live across the
    wire, so this shim answers from the proxy's outstanding-flight
    ledger (exact: one outstanding seq == one bound remote slot)."""

    def __init__(self, proxy: "RemoteDecodeWorker", cfg: PagedConfig):
        self._proxy = proxy
        self.cfg = cfg
        self.scratch_page_count = 0

    @property
    def active_slot_count(self) -> int:
        return len(self._proxy._outstanding)

    @property
    def seq_lens(self) -> np.ndarray:
        out = np.zeros(self.cfg.max_slots, np.int32)
        for i, (_fl, n_tok, _t) in enumerate(
            list(self._proxy._outstanding.values())[: self.cfg.max_slots]
        ):
            out[i] = n_tok
        return out

    def release_scratch(self) -> int:
        return 0


class RemoteDecodeWorker:
    """The front-side proxy for one decode-host process.

    Duck-types the `DecodeWorker` surface the front schedules against
    (validate/admit/step/kill/stats/headroom/free_slots/idle), with:

    - ``validate`` checking the handoff against the peer's HANDSHAKE
      identity — params/catalog/layout/kv_dtype skew is refused typed on
      the front, before any bytes cross the wire (the host re-validates
      on receipt as the backstop);
    - ``admit`` enqueueing the frame to this peer's send thread and
      returning immediately — the front's runtime thread never blocks
      on a slow host, and slot accounting is the outstanding-seq ledger;
    - ``step`` draining receipts on the front's runtime thread (the
      single-writer discipline: futures resolve where every other
      worker's do);
    - reader/sender thread errors marking the proxy ``dead``, which the
      front's pump reaps exactly like an in-process worker kill.
    """

    role = "decode"
    owns_pool = False

    def __init__(self, addr: str, *, transport: SocketTransport, metrics,
                 counters: dict, flight_recorder, worker_id: str = "",
                 expected_head: Optional[str] = None,
                 replica_id: Optional[str] = None,
                 connect_timeout: float = 5.0,
                 connect_retries: int = 40,
                 hello_timeout: float = 600.0,
                 send_timeout: float = 60.0,
                 tracer=None, logger: Optional[logging.Logger] = None):
        self.peer_addr = addr
        self.transport = transport
        self.metrics = metrics
        self._counters = counters  # the FRONT's counter dict (shared)
        self._flight = flight_recorder
        self.worker_id = worker_id or f"remote:{addr}"
        self._expected_head = expected_head
        self.replica_id = replica_id
        self._connect_timeout = connect_timeout
        self._connect_retries = int(connect_retries)
        self._hello_timeout = hello_timeout
        self._send_timeout = send_timeout
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._log = logger or logging.getLogger("genrec_tpu")
        self.dead = False
        self.draining = False
        self.identity: Optional[dict] = None
        self.head = None  # set by the front after the handshake
        self.params_step: Optional[int] = None
        self.warmup_compiles = 0
        self.admitted = 0
        self._seq = 0
        # seq -> (flight, n_tokens, t_enqueued): accepted, unresolved.
        # Runtime-thread writes only (admit/step/kill under the front's
        # runtime lock); the reader/sender threads never touch it.
        self._outstanding: dict[int, tuple] = {}
        self._inbox: queue.Queue = queue.Queue()
        self._send_q: queue.Queue = queue.Queue()
        self._sock: Optional[socket_mod.socket] = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._peer_stats: dict = {}
        self._stats_gen = 0
        self._stats_next = 0.0
        self.pool: Optional[_RemotePoolStats] = None

    # -- lifecycle -----------------------------------------------------------

    def warmup(self) -> None:
        """Connect + handshake (idempotent). The peer compiles its grid
        before accepting, so connect retries ride out host warmup; the
        HELLO read then waits on a generous timeout."""
        if self._sock is not None:
            return
        host, _, port = self.peer_addr.rpartition(":")
        last_err: Optional[Exception] = None
        for attempt in range(self._connect_retries + 1):
            try:
                sock = socket_mod.create_connection(
                    (host, int(port)), timeout=self._connect_timeout
                )
                break
            except OSError as e:
                last_err = e
                self.transport.net_counters["connect_retries"] += 1
                time.sleep(min(0.25 * (attempt + 1), 2.0))
        else:
            raise WorkerLostError(
                f"decode host {self.peer_addr} unreachable after "
                f"{self._connect_retries} retries: {last_err}"
            )
        self.transport.net_counters["connects"] += 1
        sock.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
        sock.settimeout(self._hello_timeout)
        try:
            ftype, meta, _ = recv_frame(sock)
        except (OSError, ConnectionError) as e:
            sock.close()
            raise WorkerLostError(
                f"decode host {self.peer_addr} died during handshake: {e}"
            ) from e
        if ftype != HELLO:
            sock.close()
            raise HandoffRefusedError(
                f"decode host {self.peer_addr} opened with frame type "
                f"{ftype}, expected HELLO"
            )
        if (self._expected_head is not None
                and meta.get("head") != self._expected_head):
            sock.close()
            raise HandoffRefusedError(
                f"decode host {self.peer_addr} serves head "
                f"{meta.get('head')!r}, this pool needs "
                f"{self._expected_head!r}"
            )
        self.identity = meta
        self.params_step = meta.get("params_step")
        self.warmup_compiles = int(meta.get("warmup_compiles", 0))
        self.pool = _RemotePoolStats(self, PagedConfig(
            max_slots=int(meta["max_slots"]),
            page_size=int(meta["page_size"]),
            pages_per_slot=int(meta["pages_per_slot"]),
            kv_dtype=str(meta.get("kv_dtype", "float32")),
        ))
        sock.settimeout(self._send_timeout)
        self._sock = sock
        for fn, name in ((self._send_loop, "send"), (self._recv_loop, "recv")):
            t = threading.Thread(
                target=fn, name=f"disagg-net-{name}-{self.peer_addr}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _ledger(self, operands_only: bool = False) -> None:
        """The peer budgets its own HBM (DecodeWorker._ledger in its
        process, refusing at ITS warmup); nothing is resident here."""

    def close(self, timeout: float = 5.0) -> None:
        """Graceful: ask the peer to drain and exit (and let the send
        thread actually flush the SHUTDOWN frame), then tear down the
        threads/socket. Safe to call twice."""
        if self._sock is not None and not self.dead:
            self._send_q.put((SHUTDOWN, {}, b"", None))
            deadline = time.monotonic() + min(timeout, 2.0)
            while (not self._send_q.empty() and not self.dead
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            # Give the peer's BYE time to land (the recv thread exits on
            # it) so tearing the socket down never races its last write.
            for t in self._threads:
                if "recv" in t.name:
                    t.join(min(timeout, 5.0))
        self._shutdown(timeout)

    def _shutdown(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._send_q.put(None)  # unblock the sender
        for t in self._threads:
            t.join(timeout)
        self._threads = []
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    @property
    def sockets_closed(self) -> bool:
        return self._sock is None

    # -- scheduling surface (front runtime thread) ---------------------------

    @property
    def max_slots(self) -> int:
        return int(self.identity["max_slots"]) if self.identity else 0

    @property
    def free_slots(self) -> int:
        if self.dead:
            return 0
        return max(self.max_slots - len(self._outstanding), 0)

    @property
    def idle(self) -> bool:
        return not self._outstanding

    def occupancy(self) -> float:
        total = self.max_slots or 1
        return round(len(self._outstanding) / total, 4)

    def headroom(self) -> float:
        if self.dead or self.draining:
            return -1.0
        return round(self.free_slots / (self.max_slots or 1), 4)

    @property
    def recompilations(self) -> int:
        return int(self._peer_stats.get("recompilations", 0))

    @property
    def decode_steps(self) -> int:
        return int(self._peer_stats.get("decode_steps", 0))

    def validate(self, handoff: KVHandoff) -> None:
        """The admission contract, enforced on the SEND side against the
        peer's handshake identity: skew refuses typed before the frame
        is built, let alone sent. The host's own `DecodeWorker.validate`
        re-checks on receipt (REFUSED frame) as the backstop."""
        ident = self.identity
        if ident is None or self.dead:
            raise WorkerLostError(
                f"decode host {self.peer_addr} is not connected"
            )
        if handoff.head != ident["head"]:
            raise HandoffRefusedError(
                f"handoff for head {handoff.head!r} routed to remote "
                f"{ident['head']!r} decode host {self.peer_addr}"
            )
        if list(handoff.layout) != list(ident["layout"]):
            raise HandoffRefusedError(
                f"handoff KV layout {tuple(handoff.layout)} != decode "
                f"host {self.peer_addr}'s {tuple(ident['layout'])}"
            )
        if handoff.kv_dtype != ident.get("kv_dtype", "float32"):
            raise HandoffRefusedError(
                f"handoff KV pages are {handoff.kv_dtype} but decode "
                f"host {self.peer_addr} stores "
                f"{ident.get('kv_dtype')!r} — refusing to mix page "
                "storage dtypes across the wire"
            )
        if handoff.params_step != ident.get("params_step"):
            raise HandoffRefusedError(
                f"handoff prefilled at params step {handoff.params_step} "
                f"but decode host {self.peer_addr} serves step "
                f"{ident.get('params_step')} — refusing to mix params "
                "versions across the wire"
            )
        if handoff.catalog_version != ident.get("catalog_version"):
            raise HandoffRefusedError(
                f"handoff catalog {handoff.catalog_version} != decode "
                f"host {self.peer_addr}'s {ident.get('catalog_version')} "
                "— refusing to decode against a different corpus"
            )

    def admit(self, flight: Flight, handoff: KVHandoff) -> bool:
        """Accept one validated handoff for this peer: ledger the seq,
        hand the frame to the send thread, return. False when the peer's
        slots are all spoken for (the handoff stays pending at the
        front, same as a full local pool)."""
        if self.dead or self.free_slots == 0:
            return False
        wire = handoff.wire
        if wire is None:
            raise HandoffRefusedError(
                "socket transport needs serialized handoffs (no wire "
                "bytes on this one — was it sent through the in-process "
                "transport?)"
            )
        seq = self._seq
        self._seq += 1
        req = flight.req
        meta = {
            "seq": seq,
            "req": {
                "head": req.head,
                "history": np.asarray(req.history).tolist(),
                "user_id": int(req.user_id),
                "timestamps": (np.asarray(req.timestamps).tolist()
                               if req.timestamps is not None else None),
            },
        }
        self._outstanding[seq] = (flight, int(handoff.n_tokens),
                                  time.monotonic())
        self.transport.in_flight_frames += 1
        self._send_q.put((HANDOFF, meta, wire, flight.trace))
        self.transport.release(handoff)  # frame owns the bytes now
        self.admitted += 1
        self.metrics.record_admit(1)
        return True

    def step(self) -> bool:
        """Drain receipts on the front's runtime thread — RESULTs
        resolve futures, REFUSEDs fail them typed, STATS refresh the
        peer snapshot. Also keeps a low-rate STATS_REQ heartbeat going
        so peer counters stay fresh without a per-request round trip."""
        progressed = False
        while True:
            try:
                ftype, meta, payload = self._inbox.get_nowait()
            except queue.Empty:
                break
            progressed |= self._dispatch(ftype, meta, payload)
        now = time.monotonic()
        if (not self.dead and self._sock is not None
                and now >= self._stats_next):
            self._stats_next = now + 0.25
            self._send_q.put((STATS_REQ, {}, b"", None))
        return progressed

    def _dispatch(self, ftype: int, meta: dict, payload: bytes) -> bool:
        if ftype == STATS:
            self._peer_stats = meta
            self._stats_gen += 1
            return False
        if ftype == BYE:
            return False
        if ftype not in (RESULT, REFUSED):
            self._log.warning(
                f"disagg: unexpected frame type {ftype} from "
                f"{self.peer_addr}"
            )
            return False
        ent = self._outstanding.pop(meta.get("seq"), None)
        if ent is None:
            return False
        self.transport.in_flight_frames = max(
            0, self.transport.in_flight_frames - 1)
        self.transport.net_counters["receipts"] += 1
        flight, _n_tok, _t = ent
        if ftype == REFUSED:
            err_cls = (HandoffRefusedError
                       if meta.get("etype") == "HandoffRefusedError"
                       else ServingError)
            self._counters["handoffs_refused"] += 1
            self._flight.record(
                "handoff_refused", peer=self.peer_addr,
                worker=self.worker_id, reason=meta.get("error", ""),
            )
            if not flight.fut.done():
                flight.fut.set_exception(err_cls(
                    f"decode host {self.peer_addr} refused: "
                    f"{meta.get('error', '')}"
                ))
                self.metrics.record_failure(1)
            return True
        with np.load(io.BytesIO(payload)) as z:
            items = np.array(z["items"])
            scores = np.array(z["scores"])
            sem_ids = np.array(z["sem_ids"]) if "sem_ids" in z.files else None
        resp = Response(
            head=meta["head"], items=items, scores=scores, sem_ids=sem_ids,
            params_step=meta.get("params_step"),
            bucket=tuple(meta["bucket"]),
            queue_wait_s=float(meta.get("queue_wait_s", 0.0)),
            compute_s=float(meta.get("compute_s", 0.0)),
            total_s=time.monotonic() - flight.t_enq,
            catalog_version=meta.get("catalog_version"),
            request_id=(flight.trace.trace_id
                        if flight.trace is not None else None),
            replica_id=self.replica_id,
            prefill_worker_id=meta.get("prefill_worker_id"),
            decode_worker_id=meta.get("decode_worker_id", self.worker_id),
        )
        if not flight.fut.done():
            flight.fut.set_result(resp)
        self.metrics.record_response(
            resp.queue_wait_s, resp.compute_s, resp.total_s, head=resp.head
        )
        self.metrics.record_evict(1)
        return True

    def refresh_stats(self, timeout: float = 5.0) -> dict:
        """Round-trip a STATS_REQ (drain/CI path: the final "0 recompiles
        / pools clean / sockets closed" reads must be FRESH, not the
        heartbeat's last sample). Caller must be the scheduling thread."""
        if self.dead or self._sock is None:
            return dict(self._peer_stats)
        gen = self._stats_gen
        self._send_q.put((STATS_REQ, {}, b"", None))
        deadline = time.monotonic() + timeout
        while (self._stats_gen == gen and not self.dead
               and time.monotonic() < deadline):
            self.step()
            time.sleep(0.005)
        return dict(self._peer_stats)

    # -- failure -------------------------------------------------------------

    def _on_peer_lost(self, where: str, err: Exception) -> None:
        if self.dead:
            return
        self.dead = True
        self.transport.net_counters["peer_losses"] += 1
        self._flight.record(
            "disagg_peer_lost", peer=self.peer_addr, worker=self.worker_id,
            where=where, error=str(err),
            outstanding=len(self._outstanding),
        )
        self._log.warning(
            f"disagg: decode host {self.peer_addr} lost ({where}: {err}) "
            f"with {len(self._outstanding)} frames outstanding"
        )

    def kill(self) -> list[Flight]:
        """Reap: every accepted-unresolved flight is stranded (its KV
        lives in the dead process). The front re-submits each typed,
        at most once — `DecodeWorker.kill`'s contract, across the wire."""
        self.dead = True
        stranded = []
        for seq, (flight, _n, _t) in list(self._outstanding.items()):
            if not flight.fut.done():
                stranded.append(flight)
        self.transport.in_flight_frames = max(
            0, self.transport.in_flight_frames - len(self._outstanding))
        self._outstanding.clear()
        self._shutdown()
        return stranded

    # -- I/O threads ---------------------------------------------------------

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._send_q.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                break
            ftype, meta, payload, trace = item
            t0 = time.monotonic()
            try:
                nbytes = send_frame(self._sock, ftype, meta, payload)
            except (OSError, ConnectionError) as e:
                self._on_peer_lost("send", e)
                break
            t1 = time.monotonic()
            if ftype == HANDOFF:
                self.transport.network_ms.record(t1 - t0)
                if trace is not None and self.tracer.enabled:
                    # The network hop as its own critical-path segment
                    # (scripts/trace_report.py SEGMENT_OF), attributed
                    # to the peer that received it.
                    self.tracer.record_span(
                        "handoff_network", trace.trace_id, t0, t1,
                        parent_id=trace.parent_span_id, side="send",
                        peer=self.peer_addr, transfer_bytes=nbytes,
                        component="disagg_front", worker=self.worker_id,
                    )

    def _recv_loop(self) -> None:
        # select-gated: the blocking read only STARTS once bytes exist,
        # so the socket's timeout bounds per-chunk stalls mid-frame (a
        # genuine peer hang) without a between-frames idle timeout ever
        # firing mid-read and desyncing the stream.
        sock = self._sock
        while not self._stop.is_set():
            try:
                readable, _, _ = select.select([sock], [], [], 0.05)
            except (OSError, ValueError):
                break
            if not readable:
                continue
            try:
                frame = recv_frame(sock)
            except (OSError, ConnectionError, ValueError) as e:
                if not self._stop.is_set():
                    self._on_peer_lost("recv", e)
                break
            self._inbox.put(frame)
            if frame[0] == BYE:
                break  # graceful close: the EOF behind it is not a loss

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        peer = dict(self._peer_stats)
        return {
            "peer_addr": self.peer_addr,
            "slots_active": len(self._outstanding),
            "slots_total": self.max_slots,
            "occupancy": self.occupancy(),
            "headroom": self.headroom(),
            "admitted": self.admitted,
            "decode_steps": self.decode_steps,
            "in_flight_frames": len(self._outstanding),
            "warmup_compiles": self.warmup_compiles,
            "recompilations": self.recompilations,
            "peer": peer,
        }


# ---------------------------------------------------------------------------
# The decode-host process
# ---------------------------------------------------------------------------


def _resolve_factory(spec: str):
    """``module:function`` or ``/path/to/file.py:function`` -> callable.
    The factory runs in the CHILD process and must rebuild the exact
    head/params the front serves (same seed/config), so both sides of
    the wire agree on identity — the handshake and per-handoff
    validation then PROVE it rather than assume it."""
    mod_spec, _, fn_name = spec.rpartition(":")
    if not mod_spec or not fn_name:
        raise ValueError(f"factory spec {spec!r} is not 'module:function'")
    if mod_spec.endswith(".py"):
        import importlib.util

        m_spec = importlib.util.spec_from_file_location(
            "_genrec_decode_factory", mod_spec)
        module = importlib.util.module_from_spec(m_spec)
        m_spec.loader.exec_module(module)
    else:
        import importlib

        module = importlib.import_module(mod_spec)
    return getattr(module, fn_name)


class _HostFlights:
    """The host's in-flight ledger: seq -> Flight, plus the pending
    deque for handoffs that validated but found no free slot (retried
    every loop pass — the front's pending semantics, host-side)."""

    def __init__(self):
        self.flights: dict[int, Flight] = {}
        self.pending: list[tuple[int, Flight, KVHandoff]] = []


def serve_decode_host(factory: str, *, host: str = "127.0.0.1",
                      port: int = 0, worker_id: str = "remote-d0",
                      announce=None, idle_timeout: Optional[float] = None,
                      logger: Optional[logging.Logger] = None) -> dict:
    """Run one decode worker as a network peer (the child-process
    entrypoint behind ``python -m genrec_tpu.disagg.net``).

    Binds + announces the port FIRST (``GENREC_DECODE_PORT <n>`` on
    stdout — `spawn_decode_host` reads it), then builds and warms the
    real `DecodeWorker` from the factory, then accepts the front's
    connection; the front's connect/HELLO timeouts ride out warmup.
    Serves until SHUTDOWN (drain + BYE) or peer disconnect. Returns the
    final stats dict (useful when called in-process by tests)."""
    log = logger or logging.getLogger("genrec_tpu")
    srv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    srv.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(1)
    bound_port = srv.getsockname()[1]
    import sys

    out = announce if announce is not None else sys.stdout
    print(f"GENREC_DECODE_PORT {bound_port}", file=out, flush=True)

    cfg = _resolve_factory(factory)()
    head = cfg["head"]
    params = cfg["params"]
    head.on_params(params)
    mesh = None
    if cfg.get("mesh_shape"):
        from genrec_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(dict(cfg["mesh_shape"]))
    paged: PagedConfig = cfg["paged_config"]
    n_layers, n_heads, head_dim, dtype = head.paged_layout()
    pool = KVPagePool(paged, n_layers, n_heads, head_dim, dtype)
    transport = SerializingTransport()
    from genrec_tpu.obs.flight_recorder import get_flight_recorder

    worker = DecodeWorker(
        worker_id, head, params, transport=transport, pool=pool,
        owns_pool=True, ladder=cfg["ladder"], metrics=ServingMetrics(),
        flight_recorder=get_flight_recorder().scoped(
            "decode_host", worker_id=worker_id),
        params_step=cfg.get("params_step"),
        hbm_budget_bytes=cfg.get("hbm_budget_bytes"),
        mesh=mesh, model_axis=cfg.get("model_axis", "model"),
        logger=log,
    )
    worker._ledger(operands_only=True)
    worker.warmup()
    from genrec_tpu.disagg.handoff import layout_of

    hello = {
        "worker_id": worker_id,
        "head": head.name,
        "layout": list(layout_of(head)),
        "kv_dtype": paged.kv_dtype,
        "params_step": cfg.get("params_step"),
        "catalog_version": head.catalog_version,
        "max_slots": paged.max_slots,
        "page_size": paged.page_size,
        "pages_per_slot": paged.pages_per_slot,
        "warmup_compiles": worker.warmup_compiles,
        "tp_devices": int(mesh.size) if mesh is not None else 1,
    }
    srv.settimeout(idle_timeout)
    try:
        conn, peer = srv.accept()
    except socket_mod.timeout:
        srv.close()
        raise TimeoutError("no front connected before idle_timeout")
    conn.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
    conn.settimeout(60.0)  # per-chunk bound once a frame has started
    send_frame(conn, HELLO, hello)
    log.info(f"disagg host {worker_id}: serving {head.name} to {peer}")

    ledger = _HostFlights()
    draining = False

    def _host_stats() -> dict:
        return _jsonable({
            **worker.stats(),
            "pool": {
                "pages_in_use": pool.allocator.pages_in_use,
                "pages_free": pool.allocator.pages_free,
                "slots_active": pool.active_slot_count,
                "kv_tokens_resident": int(pool.seq_lens.sum()),
            },
            "transport": transport.stats(),
            "pending": len(ledger.pending),
            "in_flight": len(ledger.flights),
            "draining": draining,
        })

    def _try_admit(seq: int, fl: Flight, h: KVHandoff) -> bool:
        try:
            worker.validate(h)
            ok = worker.admit(fl, h)
        except Exception as e:  # noqa: BLE001 — refuse THIS seq typed
            transport.release(h)
            send_frame(conn, REFUSED, {
                "seq": seq, "error": str(e),
                "etype": type(e).__name__,
            })
            return True
        if not ok:
            return False
        ledger.flights[seq] = fl
        return True

    final_stats: dict = {}
    try:
        while True:
            busy = bool(ledger.flights or ledger.pending)
            # select-gated read: never start a blocking frame read on an
            # idle wire (a poll timeout mid-frame would desync it).
            readable, _, _ = select.select(
                [conn], [], [], 0.0005 if busy else 0.05)
            frame = None
            if readable:
                try:
                    frame = recv_frame(conn)
                except (OSError, ConnectionError):
                    log.warning(
                        f"disagg host {worker_id}: front disconnected")
                    break
            if frame is not None:
                ftype, meta, payload = frame
                if ftype == HANDOFF:
                    h, _k, _v = unpack_handoff(payload)
                    r = meta["req"]
                    req = Request(
                        head=r["head"],
                        history=np.asarray(r["history"], np.int64),
                        user_id=int(r["user_id"]),
                        timestamps=(np.asarray(r["timestamps"])
                                    if r.get("timestamps") is not None
                                    else None),
                        trace=h.trace,
                    )
                    fl = Flight(req)
                    if not _try_admit(meta["seq"], fl, h):
                        ledger.pending.append((meta["seq"], fl, h))
                elif ftype == STATS_REQ:
                    send_frame(conn, STATS, _host_stats())
                elif ftype == SHUTDOWN:
                    draining = True
            # Pending handoffs retry as slots free up (front semantics).
            still = []
            for seq, fl, h in ledger.pending:
                if not _try_admit(seq, fl, h):
                    still.append((seq, fl, h))
            ledger.pending = still
            worker.step()
            # Ship every finished flight's receipt.
            for seq, fl in list(ledger.flights.items()):
                if not fl.fut.done():
                    continue
                del ledger.flights[seq]
                exc = fl.fut.exception()
                if exc is not None:
                    send_frame(conn, REFUSED, {
                        "seq": seq, "error": str(exc),
                        "etype": type(exc).__name__,
                    })
                    continue
                resp = fl.fut.result()
                buf = io.BytesIO()
                arrays = {"items": np.asarray(resp.items),
                          "scores": np.asarray(resp.scores)}
                if resp.sem_ids is not None:
                    arrays["sem_ids"] = np.asarray(resp.sem_ids)
                np.savez(buf, **arrays)
                send_frame(conn, RESULT, {
                    "seq": seq,
                    "head": resp.head,
                    "params_step": resp.params_step,
                    "catalog_version": resp.catalog_version,
                    "bucket": list(resp.bucket),
                    "queue_wait_s": resp.queue_wait_s,
                    "compute_s": resp.compute_s,
                    "prefill_worker_id": resp.prefill_worker_id,
                    "decode_worker_id": worker_id,
                }, buf.getvalue())
            if draining and not ledger.flights and not ledger.pending:
                pool.release_scratch()
                final_stats = _host_stats()
                send_frame(conn, STATS, final_stats)
                send_frame(conn, BYE, {})
                break
    finally:
        try:
            conn.close()
        finally:
            srv.close()
    log.info(f"disagg host {worker_id}: drained, exiting")
    return final_stats


def spawn_decode_host(factory: str, *, host: str = "127.0.0.1",
                      worker_id: str = "remote-d0",
                      env: Optional[dict] = None,
                      startup_timeout: float = 120.0):
    """Launch `serve_decode_host` in a fresh OS process and return
    ``(Popen, "host:port")`` once the child announces its port. ``env``
    overlays os.environ — the caller pins JAX_PLATFORMS/XLA_FLAGS there
    (they must be set before the child imports jax, which is exactly
    what a fresh process guarantees)."""
    import os
    import subprocess
    import sys

    cfg = {"factory": factory, "host": host, "port": 0,
           "worker_id": worker_id}
    full_env = dict(os.environ)
    full_env.update(env or {})
    # The child must resolve genrec_tpu the same way the parent did.
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    full_env["PYTHONPATH"] = (
        repo + os.pathsep + full_env.get("PYTHONPATH", "")
    )
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import json, sys\n"
         "from genrec_tpu.disagg.net import serve_decode_host\n"
         "serve_decode_host(**json.loads(sys.argv[1]))",
         json.dumps(cfg)],
        stdout=subprocess.PIPE, env=full_env, text=True, bufsize=1,
    )
    deadline = time.monotonic() + startup_timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"decode host {worker_id} exited rc={proc.returncode} "
                "before announcing its port"
            )
        line = proc.stdout.readline()
        if line.startswith("GENREC_DECODE_PORT "):
            return proc, f"{host}:{int(line.split()[1])}"
    proc.kill()
    raise TimeoutError(
        f"decode host {worker_id} did not announce a port within "
        f"{startup_timeout}s"
    )


if __name__ == "__main__":
    import sys

    serve_decode_host(**json.loads(sys.argv[1]))
