"""The typed KV-handoff artifact: what a prefill worker gives a decode
worker.

A `KVHandoff` is self-describing: beside the KV payload (a page run in a
shared in-process page bank, or serialized page content for the
host-roundtrip wire) it carries everything the receiving side needs to
VALIDATE the artifact before touching it — head name, page-pool layout,
token count, the donor's prefill bucket, the post-prefill slot-state
snapshot, and full provenance (params_step / catalog_version /
prefill_worker_id). Receipt validation is a typed refusal
(`HandoffRefusedError`), never silent mixing: a decode worker serving
params step N must not generate from KV a prefill worker encoded at step
M, and a catalog-version mismatch would beam-search against the wrong
trie.

The WIRE format (`pack_handoff`/`unpack_handoff`) is the cross-host
contract, pinned by ``WIRE_VERSION`` and tests/test_disagg.py: a JSON
header (provenance + layout + request lineage + array manifest)
followed by raw little-endian array bytes, framed inside one ``.npz``
container. The
serializing in-process transport round-trips every handoff through it,
so a future cross-host backend is a transport swap — the bytes already
mean the same thing on both sides.
"""

from __future__ import annotations

import dataclasses
import io
import json
from typing import Optional

import numpy as np

from genrec_tpu.obs.spans import TraceContext
from genrec_tpu.serving.types import ServingError

#: Bump when the pack/unpack layout changes; unpack refuses other
#: versions (typed) instead of misreading bytes.
#: v2: the header carries the request's lineage (``trace`` —
#: obs.TraceContext as {trace_id, parent_span_id, origin}), so the
#: decode side of a cross-host hop attaches its spans to the SAME
#: rooted trace the router/prefill side started (docs/OBSERVABILITY.md
#: "Request lineage"). v1 payloads are refused typed like any other
#: version skew.
#: v3: quantized KV (docs/SERVING.md "Quantized serving") — the header
#: carries ``kv_dtype``, and int8 payloads ship per-layer
#: ``k_scale{i}``/``v_scale{i}`` fp32 per-page-row scale planes beside
#: the int8 page content (the 2-4x wire shrink the quantized pool buys
#: travels the wire too). v2 payloads are refused typed.
WIRE_VERSION = 3


class DisaggError(ServingError):
    """Base class for disaggregated-serving errors."""


class HandoffRefusedError(DisaggError):
    """The receiving worker rejected a `KVHandoff` at validation time —
    wrong head, incompatible page layout, params/catalog version skew, or
    an unknown wire version. The refusal is the accounting: the request
    fails typed (and is counted/narrated) instead of decoding against
    mismatched state."""


class WorkerLostError(DisaggError):
    """The decode worker holding this request's KV died mid-flight and
    the typed at-most-once re-submit (back through a surviving
    prefill/decode pair — the KV died with the worker) could not complete
    it. Mirrors fleet.ReplicaLostError one level down: accepted work is
    never silently dropped."""


@dataclasses.dataclass
class KVHandoff:
    """One request's prefilled KV state, in flight between roles.

    ``layout`` is ``(n_layers, n_heads, head_dim, dtype_str)`` — the KV
    tensor geometry both sides must share (`layout_of`). Page SIZE is
    the transport's concern: the in-process tier shares one bank (views
    must match its geometry at construction), and the serializing tier
    re-checks the wire content's page size against the receiving pool
    at admit. ``init`` is the
    donor's post-prefill slot-state rows (host numpy, None/empty when the
    head's prefill leaves state zeroed — TIGER); the receiving worker
    patches bucket-dependent fields against the request's OWN bucket via
    ``head.paged_warm_state`` (the prefix-cache warm-admission semantics:
    a handoff is a warm admission whose donor ran on another worker).

    Payload is exactly one of:

    - ``pages`` — a page run in the SHARED page bank (in-process
      zero-copy transport; the handoff holds one allocator ref per page
      until it is admitted or dropped);
    - ``wire`` — the serialized page content (`pack_handoff` bytes, the
      host-roundtrip transport / future cross-host hop).
    """

    head: str
    n_tokens: int
    bucket: tuple[int, int]
    layout: tuple
    init: Optional[dict]
    params_step: Optional[int]
    catalog_version: Optional[str]
    prefill_worker_id: str
    warm: bool = False          # served from the prefill worker's prefix cache
    #: Request lineage (obs.TraceContext): rides the handoff by
    #: reference on the in-process tier and inside the wire header on
    #: the serializing tier, so the receiving decode worker's spans
    #: attach under the same trace the prefill side recorded into.
    trace: Optional[TraceContext] = None
    #: Page-pool storage dtype ("float32" | "int8") of the KV this
    #: handoff carries. Both sides must agree — a decode pool reading
    #: int8 rows as fp32 (or vice versa) would be silent garbage, so
    #: ``DecodeWorker.validate`` refuses skew typed.
    kv_dtype: str = "float32"
    pages: Optional[list] = None
    wire: Optional[bytes] = None

    @property
    def transfer_bytes(self) -> int:
        """Bytes that crossed the transport: the wire size, or 0 for the
        zero-copy in-process path (pages move by reference)."""
        return len(self.wire) if self.wire is not None else 0


def layout_of(head) -> tuple:
    """The handoff-validation layout tuple for one paged head + the
    page geometry it serves under (page_size from the pool config)."""
    n_layers, n_heads, head_dim, dtype = head.paged_layout()
    return (int(n_layers), int(n_heads), int(head_dim),
            np.dtype(dtype).name)


def pack_handoff(handoff: KVHandoff, k_content, v_content) -> bytes:
    """Serialize one handoff + its page content to the pinned wire
    format. ``k_content``/``v_content`` are per-layer host arrays shaped
    ``(n_pages_used, page_size, n_heads, head_dim)`` — exactly the pages
    the run covers, no padding (the receiving side re-pads to its own
    fixed scatter shape). For an int8 handoff (``handoff.kv_dtype ==
    "int8"``) each layer entry is a ``(data, scale)`` pair — int8 page
    rows plus their fp32 ``(n_pages_used, page_size)`` scale plane —
    and the scales ship as ``k_scale{i}``/``v_scale{i}`` arrays."""
    quantized = handoff.kv_dtype == "int8"
    header = {
        "wire_version": WIRE_VERSION,
        "head": handoff.head,
        "n_tokens": int(handoff.n_tokens),
        "bucket": list(handoff.bucket),
        "layout": list(handoff.layout),
        "kv_dtype": handoff.kv_dtype,
        "params_step": handoff.params_step,
        "catalog_version": handoff.catalog_version,
        "prefill_worker_id": handoff.prefill_worker_id,
        "warm": bool(handoff.warm),
        "trace": (handoff.trace.to_header()
                  if handoff.trace is not None else None),
        "n_layers": len(k_content),
        "state_keys": sorted(handoff.init) if handoff.init else [],
    }
    arrays = {"__header__": np.frombuffer(
        json.dumps(header).encode("utf-8"), np.uint8)}
    for i, (k, v) in enumerate(zip(k_content, v_content)):
        if quantized:
            arrays[f"k{i}"] = np.ascontiguousarray(k[0])
            arrays[f"k_scale{i}"] = np.ascontiguousarray(k[1])
            arrays[f"v{i}"] = np.ascontiguousarray(v[0])
            arrays[f"v_scale{i}"] = np.ascontiguousarray(v[1])
        else:
            arrays[f"k{i}"] = np.ascontiguousarray(k)
            arrays[f"v{i}"] = np.ascontiguousarray(v)
    for key in header["state_keys"]:
        arrays[f"s_{key}"] = np.ascontiguousarray(handoff.init[key])
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def unpack_handoff(data: bytes) -> tuple[KVHandoff, tuple, tuple]:
    """Wire bytes -> (handoff, k_content, v_content). Refuses unknown
    wire versions typed — misreading a future layout as this one would
    be silent corruption, the one failure mode the format exists to
    prevent."""
    with np.load(io.BytesIO(data)) as z:
        header = json.loads(bytes(z["__header__"]).decode("utf-8"))
        if header.get("wire_version") != WIRE_VERSION:
            raise HandoffRefusedError(
                f"handoff wire version {header.get('wire_version')!r} != "
                f"supported {WIRE_VERSION}; refusing to decode bytes under "
                "the wrong layout"
            )
        n_layers = int(header["n_layers"])
        kv_dtype = header.get("kv_dtype", "float32")
        if kv_dtype == "int8":
            k_content = tuple(
                (z[f"k{i}"], z[f"k_scale{i}"]) for i in range(n_layers)
            )
            v_content = tuple(
                (z[f"v{i}"], z[f"v_scale{i}"]) for i in range(n_layers)
            )
        else:
            k_content = tuple(z[f"k{i}"] for i in range(n_layers))
            v_content = tuple(z[f"v{i}"] for i in range(n_layers))
        init = {key: z[f"s_{key}"] for key in header["state_keys"]} or None
    handoff = KVHandoff(
        head=header["head"],
        n_tokens=int(header["n_tokens"]),
        bucket=tuple(header["bucket"]),
        layout=tuple(header["layout"]),
        init=init,
        params_step=header["params_step"],
        catalog_version=header["catalog_version"],
        prefill_worker_id=header["prefill_worker_id"],
        warm=bool(header["warm"]),
        trace=TraceContext.from_header(header.get("trace")),
        kv_dtype=kv_dtype,
        wire=data,
    )
    return handoff, k_content, v_content
