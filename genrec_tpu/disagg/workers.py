"""Role-specialized serving workers: prefill (history encode) and decode
(suffix generation), joined by typed `KVHandoff`s.

COBRA's history prefill and its suffix-step decode have completely
different arithmetic-intensity profiles (TPLA, arxiv 2508.15881): the
prefill is a bucketed batch encode that saturates on queue depth, the
decode is a slot-resident continuous loop that saturates on slot
occupancy. Splitting them into role pools lets each scale on its own
signal; the transfer unit is the refcounted page run + post-prefill
state snapshot the PR-11 prefix cache already retains.

- `PrefillWorker` owns admission: the deadline-coalesced bucket-sized
  groups of serving/engine.py, the SAME AOT prefill bucket grid, and a
  per-worker `PrefixIndex` — a warm full-history hit hands off the
  retained run without touching the prefill executable. Every completed
  prefill (warm or cold) becomes a `KVHandoff` through the configured
  `KVTransport`.
- `DecodeWorker` owns slot-level continuous batching over decode-only
  executables (the engine's collapsed slot-shape ladder) and its OWN
  `MemoryLedger` budget: ``hbm_budget_bytes`` is enforced at warmup
  (typed `HBMBudgetError` refusal) against the decode-side model —
  params + page pool + slot state + decode executables — with the
  prefill worker budgeted separately (PR 10's "per-worker budget" next
  step). Handoffs are VALIDATED on receipt: head/layout/params_step/
  catalog_version skew is a typed `HandoffRefusedError`, never silent
  mixing.

Every handoff admission uses the warm-admission semantics pinned by
tests/test_prefix_cache.py: state rows are patched against the request's
OWN history bucket (`head.paged_warm_state`), so a disagg answer equals
the co-located engine's solo serving of the same request bit-for-bit —
the parity bar scripts/check_disagg.py holds.

Threading: all worker methods run on the front's single runtime thread
(the engine's single-writer pool discipline, kept across the split);
submit threads only touch the queue under the front's lock.
"""

from __future__ import annotations

import collections
import logging
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from genrec_tpu.disagg.handoff import (
    HandoffRefusedError,
    KVHandoff,
    layout_of,
)
from genrec_tpu.obs.memory import MemoryLedger, tree_nbytes
from genrec_tpu.obs.spans import NULL_TRACER
from genrec_tpu.serving.aot import donate_argnums as _donate, sds_tree as _sds
from genrec_tpu.serving.kv_pool import (
    KVPagePool,
    PoolExhausted,
    PrefixIndex,
)
from genrec_tpu.serving.types import HBMBudgetError, Response


def _stage(tree, mesh):
    """Per-call operands (batch arrays, slot state, block tables) on
    their way into a compiled executable. Single device: device arrays,
    as always. Under a mesh: HOST arrays — the mesh-lowered executable
    places them to its expected (replicated) sharding at dispatch,
    whereas a device-0-committed jnp array would be rejected as a
    sharding mismatch (the engine's ``ServingEngine._stage``, shared by
    both role workers)."""
    import jax
    import jax.numpy as jnp

    f = np.asarray if mesh is not None else jnp.asarray
    return jax.tree_util.tree_map(f, tree)


def _place_worker(worker, mesh, model_axis: str) -> None:
    """The DecodeWorker/PrefillWorker ``mesh=`` knob: shard params by
    ``serve_rules`` (row-sharded retrieval item table incl. the int8
    QuantizedTable operand, vocab-sharded TIGER output head), commit the
    head's runtime operands, and — when this worker OWNS its pool
    (serializing/socket tiers) — shard the KV page bank over the head
    axis. A shared in-process bank is the front's to place, not one
    view's. Runs before warmup so aot.sds_tree carries every
    NamedSharding into the lowerings."""
    from genrec_tpu.parallel.shardings import (
        kv_pool_sharding,
        serve_rules,
        shard_params,
    )

    worker.params = shard_params(
        mesh, worker.params, serve_rules(model_axis), log_fn=worker._log.info
    )
    worker.head.place_operands(mesh, model_axis)
    if worker.owns_pool:
        n_heads = layout_of(worker.head)[1]
        place = kv_pool_sharding(mesh, n_heads, model_axis)
        if place is not None:
            worker.pool.place(place)


class Flight:
    """One accepted request moving through the role pipeline."""

    __slots__ = ("req", "fut", "t_enq", "retried", "trace")

    def __init__(self, req, fut: Optional[Future] = None,
                 t_enq: Optional[float] = None, retried: bool = False):
        self.req = req
        self.fut = fut if fut is not None else Future()
        self.t_enq = t_enq if t_enq is not None else time.monotonic()
        self.retried = retried  # at-most-once worker-loss re-submit spent
        # Request lineage (obs.TraceContext), parented under the front's
        # per-request span — set by DisaggFront.submit; every worker
        # span for this flight attaches here. Survives re-submit after
        # a worker death, so the retry stays in the ORIGINAL trace.
        self.trace = None


class PrefillWorker:
    """Admission + bucket-ladder prefill; emits typed `KVHandoff`s.

    ``pool`` is either a slot view over the shared in-process page bank
    (zero-copy transport) or this worker's own staging pool (serializing
    transport; ``owns_pool=True`` budgets its bytes here). The worker
    never binds slots — prefill writes through raw page runs, and the
    run's ownership moves to the handoff (and, when the prefix cache
    retains it, to the index) the moment the executable returns.
    """

    role = "prefill"

    def __init__(self, worker_id: str, head, params, *, ladder, transport,
                 pool: KVPagePool, owns_pool: bool, max_batch: int,
                 max_wait_s: float, metrics, flight_recorder,
                 params_step: Optional[int] = None, prefix_cache: bool = True,
                 prefix_cache_entries: int = 4096,
                 hbm_budget_bytes: Optional[int] = None,
                 tracer=None,
                 mesh=None, model_axis: str = "model",
                 logger: Optional[logging.Logger] = None):
        self.worker_id = worker_id
        self.head = head
        self.params = params
        self.ladder = ladder
        self.transport = transport
        self.pool = pool
        self.owns_pool = owns_pool
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.metrics = metrics
        self._flight = flight_recorder
        self.params_step = params_step
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._log = logger or logging.getLogger("genrec_tpu")
        self._mesh = mesh
        self._model_axis = str(model_axis)
        if mesh is not None:
            _place_worker(self, mesh, self._model_axis)
        # Guarded by the FRONT's lock: submit threads append, the front
        # runtime thread pops.
        self.queue: collections.deque = collections.deque()
        # Flights already counted as deferred / prefix-looked-up: a
        # page-starved request is re-popped every pass and must count its
        # deferral (and its lookup outcome) ONCE, not per retry — the
        # engine's _oom_counted discipline.
        self._oom_counted: set[int] = set()
        self.prefix: PrefixIndex | None = (
            PrefixIndex(pool.allocator, max_entries=prefix_cache_entries)
            if prefix_cache else None
        )
        self._prefill: dict[tuple[int, int], object] = {}
        self._transport_execs: list = []
        self.warmup_compiles = 0
        self.recompilations = 0
        self._warm = False
        self.prefills = 0
        self.deferred = 0
        self.dead = False
        self.draining = False
        self.memory = MemoryLedger()
        self._hbm_budget = (
            int(hbm_budget_bytes) if hbm_budget_bytes is not None else None
        )
        self._page_nbytes = (
            tree_nbytes((pool.k_pools, pool.v_pools)) // pool.cfg.num_pages
        )

    # -- warmup --------------------------------------------------------------

    def _count_compile(self, _compiled=None) -> None:
        if self._warm:
            self.recompilations += 1
        else:
            self.warmup_compiles += 1

    def _count_transport_compile(self, compiled=None) -> None:
        # Transport executables (serializing gather/scatter) belong in
        # THIS worker's HBM model beside its own grid — omitting them
        # would let a budget pass warmup and OOM live.
        self._count_compile(compiled)
        if compiled is not None:
            self._transport_execs.append(compiled)

    def _compile_prefill(self, B: int, L: int):
        import jax
        import jax.numpy as jnp  # noqa: F401 — jax must be up

        fn = self.head.make_prefill_paged_fn(B, L)
        ops = self.head.runtime_operands()
        batch = self.head.make_batch([self.head.dummy_request()], B, L)
        args = (
            self.params,
            *(_sds(op) for op in ops),
            *(_sds(b) for b in batch),  # aval-only: never pins a device
            jax.ShapeDtypeStruct((B, self.pool.cfg.pages_per_slot), np.int32),
            _sds(self.pool.k_pools),
            _sds(self.pool.v_pools),
        )
        n = 1 + len(ops) + len(batch)
        compiled = jax.jit(
            fn, donate_argnums=_donate(n + 1, n + 2)  # k_pools, v_pools
        ).lower(*args).compile()
        self._count_compile()
        return compiled

    def warmup(self) -> None:
        # Operands-first budget check: params/catalog/pool bytes are
        # known before any executable exists, and the ledger total only
        # grows from here — refusing NOW spends zero compile time on a
        # worker that can never fit.
        self._ledger(operands_only=True)
        for B, L in self.ladder.combos():
            self._prefill[(B, L)] = self._compile_prefill(B, L)
        self.transport.prepare_send(self.pool, self._count_transport_compile)
        self._ledger()
        self._warm = True

    def _ledger(self, operands_only: bool = False) -> None:
        led = self.memory
        led.reset_group(self.worker_id)
        led.record_operand(self.worker_id, "params", tree_nbytes(self.params))
        ops = self.head.runtime_operands()
        if ops:
            led.record_operand(self.worker_id, "catalog_operands",
                               tree_nbytes(ops))
        if self.owns_pool:
            led.record_operand(
                self.worker_id, "kv_page_pool",
                tree_nbytes((self.pool.k_pools, self.pool.v_pools)),
            )
        else:
            # In-process tier: the shared page bank is not this worker's
            # to own, but it IS resident on the device this worker's
            # budget models — omit it and an impossible budget passes
            # warmup only to OOM live. (Aggregating per-worker ledgers
            # across a group double-counts the bank by design: the
            # per-worker budget is the gate, and on the cross-host tier
            # every worker really does hold its own pool.)
            led.record_operand(
                self.worker_id, "kv_page_bank_shared",
                tree_nbytes((self.pool.k_pools, self.pool.v_pools)),
            )
        led.record_reclaimable(
            self.worker_id, "prefix_cache_pages",
            (self.prefix.retained_pages if self.prefix is not None else 0)
            * self._page_nbytes,
        )
        for (B, L), ex in self._prefill.items():
            led.record_executable(self.worker_id, f"prefill/B{B}/L{L}", ex)
        for i, ex in enumerate(self._transport_execs):
            led.record_executable(self.worker_id, f"transport/{i}", ex)
        if self._hbm_budget is not None:
            summary = led.summary(budget_bytes=self._hbm_budget)
            if summary["over_budget"]:
                raise HBMBudgetError(
                    f"prefill worker {self.worker_id}: HBM model exceeds "
                    f"hbm_budget_bytes={self._hbm_budget} (predicted "
                    f"{summary['total_bytes']} bytes"
                    + (" on operands alone, before any executable"
                       if operands_only else "") + ")\n"
                    + led.breakdown_text(self._hbm_budget)
                )

    # -- the prefill pass ----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def headroom(self) -> float:
        if self.dead or self.draining:
            return -1.0
        return round(1.0 - len(self.queue) / float(4 * self.max_batch), 4)

    def _alloc_run(self, n_pages: int):
        """allocator.alloc with the prefix-reclaim ladder: retained runs
        are released LRU-first before any admission defers (the engine's
        _admit_pages discipline, per worker)."""
        try:
            return self.pool.allocator.alloc(n_pages)
        except PoolExhausted:
            if self.prefix is None or not len(self.prefix):
                raise
            evicted = self.prefix.reclaim(n_pages)
            if evicted:
                self.metrics.record_prefix_evict(self.head.name, evicted)
            return self.pool.allocator.alloc(n_pages)

    def pump(self, lock, draining: bool) -> list[tuple[Flight, KVHandoff]]:
        """One admission pass (front runtime thread): pop one deadline-
        coalesced group, serve warm hits off the prefix index, run ONE
        bucketed prefill for the cold rest, and return the handoffs for
        the front to route. Requests that can't get pages stay queued
        (deferral counted once per request is the front's concern — here
        each pass counts at most one deferral episode)."""
        now = time.monotonic()
        with lock:
            if not self.queue:
                return []
            if (
                len(self.queue) < self.max_batch
                and now - self.queue[0].t_enq < self.max_wait_s
                and not (draining or self.draining)
            ):
                return []
            group = [self.queue.popleft()
                     for _ in range(min(len(self.queue), self.max_batch))]
        t_pop = time.monotonic()
        head = self.head
        max_hist = self.ladder.history_buckets[-1]
        out: list[tuple[Flight, KVHandoff]] = []
        warm, cold = [], []
        for fl in group:
            own_L = self.ladder.history_bucket(
                max(head.natural_len(fl.req), 1))
            n_tok = head.paged_kv_tokens(head.natural_len(fl.req), own_L)
            key = (head.prefix_key_tokens(fl.req, max_hist)
                   if self.prefix is not None else None)
            entry = None
            if key is not None:
                t0 = time.monotonic()
                entry, matched = self.prefix.lookup(key)
                if entry is not None and entry.n_tokens != n_tok:
                    entry = None  # same key, different KV footprint: cold
                outcome = ("hit" if entry is not None
                           else ("partial" if matched else "miss"))
                if id(fl) not in self._oom_counted:
                    self.metrics.record_prefix_lookup(
                        head.name, outcome,
                        tokens=entry.n_tokens if entry is not None else 0,
                    )
                    if fl.trace is not None:
                        self.tracer.record_span(
                            "prefix_lookup", fl.trace.trace_id, t0,
                            time.monotonic(),
                            parent_id=fl.trace.parent_span_id,
                            outcome=outcome, matched_tokens=int(matched),
                            **self._span_ident(),
                        )
            if entry is not None:
                warm.append((fl, entry))
            else:
                cold.append((fl, key, n_tok))
        for fl, entry in warm:
            self._oom_counted.discard(id(fl))
            t0 = time.monotonic()
            handoff = self._make_handoff(
                entry.n_tokens, entry.bucket, entry.init, warm=True,
                trace=fl.trace)
            try:
                tw0 = time.monotonic()
                self.transport.send(self.pool, entry.pages, handoff)
                tw1 = time.monotonic()
            except Exception as e:  # noqa: BLE001 — fail THIS flight only
                # The flight is already popped from the queue: anything
                # escaping pump() would strand its future unresolved
                # (the retained prefix entry itself is untouched).
                self._log.exception(
                    f"disagg: warm handoff send failed on worker "
                    f"{self.worker_id}"
                )
                if not fl.fut.done():
                    fl.fut.set_exception(e)
                self.metrics.record_failure(1)
                continue
            self.prefix.touch(entry.key)
            entry.hits += 1
            if fl.trace is not None:
                self._record_handoff_spans(
                    fl, t_pop, warm_t0=t0,
                    wire=(tw0, tw1, handoff.transfer_bytes))
            out.append((fl, handoff))
        if cold:
            out.extend(self._prefill_cold(cold, lock, t_pop))
        self._publish_reclaimable()
        return out

    def _span_ident(self) -> dict:
        return {"component": "prefill_worker", "worker": self.worker_id}

    def _record_handoff_spans(self, fl: Flight, t_pop: float, *,
                              warm_t0: float | None = None,
                              admission=None, prefill=None,
                              wire=None) -> None:
        """One flight's prefill-side span set, attached under the
        front's per-request span (fl.trace.parent_span_id):
        queue_wait, then warm_admit OR admission+prefill, then the
        send side of handoff_wire."""
        tr = fl.trace
        ident = self._span_ident()
        rs = self.tracer.record_span
        rs("queue_wait", tr.trace_id, fl.t_enq, t_pop,
           parent_id=tr.parent_span_id, **ident)
        if warm_t0 is not None:
            rs("warm_admit", tr.trace_id, warm_t0, time.monotonic(),
               parent_id=tr.parent_span_id, **ident)
        if admission is not None:
            rs("admission", tr.trace_id, admission[0], admission[1],
               parent_id=tr.parent_span_id, **ident)
        if prefill is not None:
            t0, t1, B, L = prefill
            rs("prefill", tr.trace_id, t0, t1,
               parent_id=tr.parent_span_id, bucket_b=B, bucket_l=L,
               **ident)
        if wire is not None:
            tw0, tw1, nbytes = wire
            rs("handoff_wire", tr.trace_id, tw0, tw1,
               parent_id=tr.parent_span_id, side="send",
               transport=self.transport.name, transfer_bytes=int(nbytes),
               **ident)

    def _make_handoff(self, n_tokens: int, bucket, init, warm: bool,
                      trace=None):
        return KVHandoff(
            head=self.head.name, n_tokens=int(n_tokens), bucket=bucket,
            layout=layout_of(self.head), init=init,
            params_step=self.params_step,
            catalog_version=self.head.catalog_version,
            prefill_worker_id=self.worker_id, warm=warm, trace=trace,
            kv_dtype=self.pool.cfg.kv_dtype,
        )

    def _prefill_cold(self, cold, lock,
                      t_pop: float) -> list[tuple[Flight, KVHandoff]]:
        head = self.head
        t_alloc0 = time.monotonic()
        runs, admitted = [], []
        for fl, key, n_tok in cold:
            try:
                runs.append(self._alloc_run(self.pool.cfg.pages_for(n_tok)))
                admitted.append((fl, key, n_tok))
            except PoolExhausted:
                break
        leftover = [fl for fl, _k, _n in cold[len(admitted):]]
        if leftover:  # out of pages: requeue at the FRONT (FIFO order)
            with lock:
                self.queue.extendleft(reversed(leftover))
            fresh = [fl for fl in leftover
                     if id(fl) not in self._oom_counted]
            if fresh:  # one deferral per request, not per retry
                self._oom_counted.update(id(fl) for fl in fresh)
                self.deferred += len(fresh)
                self.metrics.record_oom_admit(len(fresh), head=head.name)
        if not admitted:
            return []
        self._oom_counted.difference_update(
            id(fl) for fl, _k, _n in admitted)
        reqs = [fl.req for fl, _k, _n in admitted]
        L = self.ladder.history_bucket(
            max(max((head.natural_len(r) for r in reqs), default=1), 1))
        B = self.ladder.batch_bucket(len(reqs))
        compiled = self._prefill.get((B, L))
        if compiled is None:  # off-grid (should not happen): counted
            compiled = self._prefill[(B, L)] = self._compile_prefill(B, L)
        bt = np.zeros((B, self.pool.cfg.pages_per_slot), np.int32)
        for i, run in enumerate(runs):
            bt[i, : len(run)] = run
        t_run0 = time.monotonic()
        try:
            args = _stage(head.make_batch(reqs, B, L), self._mesh)
            k_pools, v_pools, init = compiled(
                self.params, *head.runtime_operands(), *args,
                _stage(bt, self._mesh), self.pool.k_pools, self.pool.v_pools,
            )
            self.pool.k_pools, self.pool.v_pools = k_pools, v_pools
        except Exception as e:  # noqa: BLE001 — fail THESE futures only
            self._log.exception(
                f"disagg: prefill on worker {self.worker_id} failed"
            )
            for run, (fl, _k, _n) in zip(runs, admitted):
                self.pool.allocator.free(run)
                if not fl.fut.done():
                    fl.fut.set_exception(e)
            self.metrics.record_failure(len(admitted))
            return []
        t_run1 = time.monotonic()
        self.prefills += len(admitted)
        self.metrics.record_batch(head.name, (B, L))
        out = []
        for i, (run, (fl, key, n_tok)) in enumerate(zip(runs, admitted)):
            snapshot = (
                {k: np.array(np.asarray(v)[i]) for k, v in init.items()}
                if init else None
            )
            if self.prefix is not None and key is not None:
                self.prefix.insert(key, n_tokens=n_tok, pages=run,
                                   init=snapshot, bucket=(B, L))
                self.metrics.record_prefix_insert(head.name)
            handoff = self._make_handoff(n_tok, (B, L), snapshot, warm=False,
                                         trace=fl.trace)
            try:
                tw0 = time.monotonic()
                self.transport.send(self.pool, run, handoff)
                tw1 = time.monotonic()
            except Exception as e:  # noqa: BLE001 — fail THIS flight only
                # Same guarantee as the warm loop: the temp alloc ref
                # still drops (no page leak in the staging pool) and the
                # popped flight fails typed instead of hanging; the
                # remaining handoffs in the group still go out.
                self._log.exception(
                    f"disagg: handoff send failed on worker "
                    f"{self.worker_id}"
                )
                self.pool.allocator.free(run)
                if not fl.fut.done():
                    fl.fut.set_exception(e)
                self.metrics.record_failure(1)
                continue
            self.pool.allocator.free(run)  # drop the temp alloc ref
            if fl.trace is not None:
                self._record_handoff_spans(
                    fl, t_pop, admission=(t_alloc0, t_run0),
                    prefill=(t_run0, t_run1, B, L),
                    wire=(tw0, tw1, handoff.transfer_bytes))
            out.append((fl, handoff))
        return out

    def _publish_reclaimable(self) -> None:
        if self.prefix is None:
            return
        s = self.prefix.stats()
        s["retained_bytes"] = s["retained_pages"] * self._page_nbytes
        self.metrics.set_prefix_gauges(self.head.name, s)
        self.memory.record_reclaimable(
            self.worker_id, "prefix_cache_pages", s["retained_bytes"]
        )

    def clear_prefix_cache(self, reason: str) -> int:
        if self.prefix is None:
            return 0
        n = self.prefix.clear()
        if n:
            self.metrics.record_prefix_evict(self.head.name, n,
                                             invalidation=True)
            self._flight.record(
                "prefix_cache_invalidated", head=self.head.name,
                worker=self.worker_id, reason=reason, entries=n,
            )
        self._publish_reclaimable()
        return n

    def stats(self) -> dict:
        out = {
            "queue_depth": len(self.queue),
            "prefills": self.prefills,
            "deferred": self.deferred,
            "warmup_compiles": self.warmup_compiles,
            "recompilations": self.recompilations,
            "headroom": self.headroom(),
            "hbm": self.memory.summary(budget_bytes=self._hbm_budget),
        }
        if self.prefix is not None:
            s = self.prefix.stats()
            s["retained_bytes"] = s["retained_pages"] * self._page_nbytes
            out["prefix_cache"] = s
        return out


class DecodeWorker:
    """Slot-level continuous batching over decode-only executables.

    With ``spec_topology`` set (the front computes one `TreeTopology`
    per spec-enabled head group), the worker compiles the tree-verify
    step INSTEAD of the plain decode step at every slot rung — the
    engine's speculative path, per worker — and reserves the scratch
    pages the tree's candidate K/V lands in out of its pool, so
    speculation never competes with handoff admissions."""

    role = "decode"

    def __init__(self, worker_id: str, head, params, *, transport,
                 pool: KVPagePool, owns_pool: bool, ladder, metrics,
                 flight_recorder, slot_floor: int = 1,
                 params_step: Optional[int] = None,
                 replica_id: Optional[str] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 spec_topology=None, spec_fanout=8,
                 tracer=None,
                 mesh=None, model_axis: str = "model",
                 logger: Optional[logging.Logger] = None):
        self.worker_id = worker_id
        self.head = head
        self.params = params
        self.transport = transport
        self.pool = pool
        self.owns_pool = owns_pool
        self.ladder = ladder
        self.metrics = metrics
        self._flight = flight_recorder
        self.params_step = params_step
        self.replica_id = replica_id
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._log = logger or logging.getLogger("genrec_tpu")
        self._mesh = mesh
        self._model_axis = str(model_axis)
        if mesh is not None:
            _place_worker(self, mesh, self._model_axis)
        cfg = pool.cfg
        self.spec_topology = spec_topology
        self.spec_fanout = spec_fanout
        if spec_topology is not None:
            # Scratch-page reservation (the engine's discipline, per
            # worker): the pool/bank the front built at CONSTRUCTION
            # already includes the demand, so reserving here never eats
            # admission capacity for the initial workers.
            per_slot = -(-spec_topology.n_nodes // cfg.page_size)
            self._scratch_demand = cfg.max_slots * per_slot
        else:
            self._scratch_demand = 0
        try:
            self._scratch_tables = pool.reserve_scratch(self._scratch_demand)
        except PoolExhausted:
            # A bank-backed worker added PAST the group's initial sizing
            # (decode role scale-out on the in-process tier): the shared
            # bank was provisioned for the construction-time worker
            # count, so this reservation may not fit. Degrade narrated
            # instead of failing the scale-out mid-construction — the
            # pure-JAX tree-verify fallback never touches the scratch
            # pages (they are the TPU kernel's landing zone), so serving
            # on this tier is unaffected; a real TPU deployment
            # re-provisions the bank instead. (On the serializing tier
            # the worker owns its pool, sized to include the demand, so
            # this path cannot fire there.)
            wanted, self._scratch_demand = self._scratch_demand, 0
            self._scratch_tables = pool.reserve_scratch(0)
            self._flight.record("spec_scratch_unreserved",
                                worker_id=worker_id, pages_wanted=wanted)
            self._log.warning(
                f"disagg: decode worker {worker_id} joined a shared bank "
                "with no room for its speculative scratch reservation — "
                "proceeding unreserved (CPU fallback unaffected)"
            )
        self.state = head.paged_state_zeros(cfg.max_slots)
        self.steps = np.zeros(cfg.max_slots, np.int32)
        self.active = np.zeros(cfg.max_slots, bool)
        # (flight, handoff, t_admit, span_ctx) per slot; span_ctx is
        # (trace_id, slot_residency_span_id, parent_span_id) or None.
        self.entries: list = [None] * cfg.max_slots
        shapes = []
        s = cfg.max_slots
        floor = max(int(slot_floor), 1)
        while True:
            shapes.append(s)
            if s <= floor:
                break
            s = max(s // 2, floor)
        self.slot_shapes = sorted(set(shapes))
        self._decode: dict[int, object] = {}
        self._spec: dict[int, object] = {}
        self._transport_execs: list = []
        self.warmup_compiles = 0
        self.recompilations = 0
        self._warm = False
        self.decode_steps = 0
        self.admitted = 0
        self.dead = False
        self.draining = False
        self.memory = MemoryLedger()
        self._hbm_budget = (
            int(hbm_budget_bytes) if hbm_budget_bytes is not None else None
        )

    # -- warmup --------------------------------------------------------------

    def _count_compile(self, _compiled=None) -> None:
        if self._warm:
            self.recompilations += 1
        else:
            self.warmup_compiles += 1

    def _count_transport_compile(self, compiled=None) -> None:
        # See PrefillWorker._count_transport_compile: the scatter
        # executable belongs in this worker's HBM model.
        self._count_compile(compiled)
        if compiled is not None:
            self._transport_execs.append(compiled)

    def _compile_decode(self, S: int):
        import jax

        fn = self.head.make_decode_paged_fn()
        ops = self.head.runtime_operands()
        return self._compile_step_fn(fn, ops, S, jax)

    def _compile_spec(self, S: int):
        """The tree-verify executable at rung S (engine's
        _PagedRunner._compile_spec, per worker): identical operand
        surface to the plain step, returns (state, accept_len)."""
        import jax

        fn = self.head.make_spec_decode_paged_fn(self.spec_fanout)
        ops = self.head.runtime_operands()
        return self._compile_step_fn(fn, ops, S, jax)

    def _compile_step_fn(self, fn, ops, S: int, jax):
        args = (
            self.params,
            *(_sds(op) for op in ops),
            _sds({k: v[:S] for k, v in self.state.items()}),
            jax.ShapeDtypeStruct((S,), np.int32),
            jax.ShapeDtypeStruct((S, self.pool.cfg.pages_per_slot), np.int32),
            jax.ShapeDtypeStruct((S,), np.int32),
            _sds(self.pool.k_pools),
            _sds(self.pool.v_pools),
        )
        # Donate the slot-state operand (argnum 2 with one trie operand —
        # the same PAGED_DECODE_DONATE_ARGNUMS discipline the engine
        # holds; graftlint audits the production entry).
        compiled = jax.jit(
            fn, donate_argnums=_donate(1 + len(ops))
        ).lower(*args).compile()
        self._count_compile()
        return compiled

    def warmup(self) -> None:
        # Operands-first (see PrefillWorker.warmup): an impossible
        # decode-side budget refuses before any compile is paid. A
        # speculative worker compiles the tree-verify step INSTEAD of
        # the plain step at every rung (the verified-rejection worst
        # case IS the plain step — the engine's discipline).
        self._ledger(operands_only=True)
        for S in self.slot_shapes:
            if self.spec_topology is not None:
                self._spec[S] = self._compile_spec(S)
            else:
                self._decode[S] = self._compile_decode(S)
        self.transport.prepare_admit(self.pool, self._count_transport_compile)
        self._ledger()
        self._warm = True

    def _ledger(self, operands_only: bool = False) -> None:
        led = self.memory
        led.reset_group(self.worker_id)
        led.record_operand(self.worker_id, "params", tree_nbytes(self.params))
        ops = self.head.runtime_operands()
        if ops:
            led.record_operand(self.worker_id, "catalog_operands",
                               tree_nbytes(ops))
        if self.owns_pool:
            led.record_operand(
                self.worker_id, "kv_page_pool",
                tree_nbytes((self.pool.k_pools, self.pool.v_pools)),
            )
        else:
            # Shared-bank slot view: see PrefillWorker._ledger — the
            # bank's bytes belong in this worker's budget model even
            # though the group owns the arrays.
            led.record_operand(
                self.worker_id, "kv_page_bank_shared",
                tree_nbytes((self.pool.k_pools, self.pool.v_pools)),
            )
        led.record_operand(self.worker_id, "paged_slot_state",
                           tree_nbytes(self.state))
        for S, ex in self._decode.items():
            led.record_executable(self.worker_id, f"decode/S{S}", ex)
        for S, ex in self._spec.items():
            led.record_executable(self.worker_id, f"spec_decode/S{S}", ex)
        for i, ex in enumerate(self._transport_execs):
            led.record_executable(self.worker_id, f"transport/{i}", ex)
        if self._hbm_budget is not None:
            summary = led.summary(budget_bytes=self._hbm_budget)
            if summary["over_budget"]:
                raise HBMBudgetError(
                    f"decode worker {self.worker_id}: HBM model exceeds "
                    f"hbm_budget_bytes={self._hbm_budget} (predicted "
                    f"{summary['total_bytes']} bytes — decode-side only: "
                    "params + page pool + slot state + decode "
                    "executables"
                    + (", refused on operands alone before any "
                       "executable" if operands_only else "") + ")\n"
                    + led.breakdown_text(self._hbm_budget)
                )

    # -- handoff receipt -----------------------------------------------------

    @property
    def idle(self) -> bool:
        return not self.active.any()

    @property
    def free_slots(self) -> int:
        return self.pool.free_slot_count

    def occupancy(self) -> float:
        total = self.pool.cfg.max_slots
        return round((total - self.pool.free_slot_count) / total, 4)

    def headroom(self) -> float:
        if self.dead or self.draining:
            return -1.0
        return round(self.pool.free_slot_count / self.pool.cfg.max_slots, 4)

    def validate(self, handoff: KVHandoff) -> None:
        """Receipt validation — every mismatch is a typed refusal. The
        handoff is self-describing precisely so this check needs nothing
        but the artifact and this worker's own identity."""
        if handoff.head != self.head.name:
            raise HandoffRefusedError(
                f"handoff for head {handoff.head!r} routed to a "
                f"{self.head.name!r} decode worker"
            )
        if tuple(handoff.layout) != layout_of(self.head):
            raise HandoffRefusedError(
                f"handoff KV layout {tuple(handoff.layout)} != this "
                f"worker's {layout_of(self.head)}"
            )
        if handoff.kv_dtype != self.pool.cfg.kv_dtype:
            raise HandoffRefusedError(
                f"handoff KV pages are {handoff.kv_dtype} but this "
                f"worker's pool stores {self.pool.cfg.kv_dtype} — "
                "refusing to mix page storage dtypes across the split "
                "(prefill and decode pools must share one kv_dtype)"
            )
        if handoff.params_step != self.params_step:
            raise HandoffRefusedError(
                f"handoff prefilled at params step {handoff.params_step} "
                f"but this worker serves step {self.params_step} — "
                "refusing to mix params versions across the split"
            )
        if handoff.catalog_version != self.head.catalog_version:
            raise HandoffRefusedError(
                f"handoff catalog {handoff.catalog_version} != this "
                f"worker's {self.head.catalog_version} — refusing to "
                "decode against a different corpus"
            )

    def admit(self, flight: Flight, handoff: KVHandoff) -> bool:
        """Bind one validated handoff into a free slot; False when the
        pool has no room NOW (the handoff stays pending at the front).
        State restore is the warm-admission semantics: rows zeroed, the
        donor snapshot written, bucket-dependent fields re-judged against
        the request's OWN bucket (head.paged_warm_state)."""
        if self.pool.free_slot_count == 0:
            return False
        try:
            slot = self.transport.admit(handoff, self.pool)
        except PoolExhausted:
            return False
        try:
            for key in self.state:
                self.state[key][slot] = 0
            if handoff.init:
                own_L = self.ladder.history_bucket(
                    max(self.head.natural_len(flight.req), 1))
                patched = self.head.paged_warm_state(
                    dict(handoff.init), handoff.n_tokens, own_L)
                for key, val in patched.items():
                    self.state[key][slot] = val
        except Exception as e:  # noqa: BLE001 — unbind, then refuse typed
            # The transport already bound the slot: a state snapshot
            # that does not fit this head (skewed peer) must not leak
            # it — evict drops the binding ref, then the typed refusal
            # rides the front's normal refusal path.
            self.pool.evict(slot)
            raise HandoffRefusedError(
                f"handoff state snapshot does not fit this worker's "
                f"slot state: {e!r}"
            ) from e
        self.steps[slot] = self.head.paged_init_step
        self.active[slot] = True
        # Slot-residency span: pre-allocate its id so the decode/spec
        # step spans recorded BEFORE the slot finishes can parent onto
        # it (the engine's allocate-before-record discipline). The
        # lineage comes off the HANDOFF — on a cross-host hop the wire
        # header is the only carrier — falling back to the flight's.
        ctx = handoff.trace if handoff.trace is not None else flight.trace
        span_ctx = None
        if ctx is not None and self.tracer.enabled:
            span_ctx = (ctx.trace_id, self.tracer.allocate_span_id(),
                        ctx.parent_span_id)
        elif ctx is not None:
            span_ctx = (ctx.trace_id, None, ctx.parent_span_id)
        self.entries[slot] = (flight, handoff, time.monotonic(), span_ctx)
        self.transport.release(handoff)
        self.admitted += 1
        self.metrics.record_admit(1)
        return True

    # -- decode --------------------------------------------------------------

    def _decode_span_ident(self) -> dict:
        return {"component": "decode_worker", "worker": self.worker_id}

    def step(self) -> bool:
        """Advance every active slot — one decode position through the
        plain step, or 1..(1 + spec_depth) positions through the
        tree-verify step when this worker speculates (the engine's
        fixed-shape step, per worker)."""
        if self.idle:
            return False
        spec = self.spec_topology is not None
        hi = int(np.nonzero(self.active)[0][-1]) + 1
        S = next(s for s in self.slot_shapes if s >= hi)
        t_stage = time.monotonic()
        mesh = self._mesh
        args = (
            self.params,
            *self.head.runtime_operands(),
            _stage({k: v[:S] for k, v in self.state.items()}, mesh),
            _stage(np.where(self.active[:S], self.steps[:S], 0)
                   .astype(np.int32), mesh),
            _stage(self.pool.block_tables[:S], mesh),
            _stage(self.pool.seq_lens[:S], mesh),
            self.pool.k_pools,
            self.pool.v_pools,
        )
        t0 = time.monotonic()
        if spec:
            out, accept = self._spec[S](*args)
        else:
            out = self._decode[S](*args)
        for k, v in out.items():
            self.state[k][:S] = np.asarray(v)
        active_idx = np.nonzero(self.active)[0]
        if spec:
            # Accept-length clamp: exactly the engine's (root level is
            # always exact, never overshoot a slot's remaining codes).
            total = self.head.paged_total_steps
            adv = np.minimum(
                np.asarray(accept)[active_idx],
                total - self.steps[active_idx],
            ).astype(np.int32)
            adv = np.maximum(adv, 1)
        t1 = time.monotonic()
        if self.tracer.enabled:
            ident = self._decode_span_ident()
            for i, slot in enumerate(active_idx):
                span_ctx = self.entries[slot][3]
                if span_ctx is None:
                    continue
                tid, sid = span_ctx[0], span_ctx[1]
                if spec:
                    self.tracer.record_span(
                        "draft", tid, t_stage, t0, parent_id=sid,
                        step=int(self.steps[slot]),
                        drafted=int(self.spec_topology.n_nodes
                                    - self.spec_topology.beams),
                        **ident,
                    )
                    self.tracer.record_span(
                        "tree_verify", tid, t0, t1, parent_id=sid,
                        step=int(self.steps[slot]), slots=S,
                        accept_len=int(adv[i]), **ident,
                    )
                else:
                    self.tracer.record_span(
                        "decode_step", tid, t0, t1, parent_id=sid,
                        step=int(self.steps[slot]), slots=S, **ident,
                    )
        if spec:
            self.steps[active_idx] += adv
            self.metrics.record_decode_step()
            self.metrics.record_spec(
                self.head.name,
                drafted=len(active_idx)
                * (self.spec_topology.n_nodes - self.spec_topology.beams),
                accept_lens=adv,
            )
            if self.tracer.enabled:
                t2 = time.monotonic()
                ident = self._decode_span_ident()
                for i, slot in enumerate(active_idx):
                    span_ctx = self.entries[slot][3]
                    if span_ctx is not None:
                        self.tracer.record_span(
                            "accept", span_ctx[0], t1, t2,
                            parent_id=span_ctx[1],
                            accept_len=int(adv[i]), **ident,
                        )
        else:
            self.steps[self.active] += 1
            self.metrics.record_decode_step()
        self.decode_steps += 1
        self.sweep_finished()
        return True

    def sweep_finished(self) -> None:
        head = self.head
        done = np.nonzero(self.active
                          & (self.steps >= head.paged_total_steps))[0]
        for slot in done:
            flight, handoff, t_admit, span_ctx = self.entries[slot]
            now = time.monotonic()
            try:
                payload = head.paged_finalize(
                    {k: np.array(v[slot]) for k, v in self.state.items()},
                    flight.req,
                )
                resp = Response(
                    head=head.name,
                    items=payload["items"],
                    scores=payload["scores"],
                    sem_ids=payload.get("sem_ids"),
                    params_step=self.params_step,
                    catalog_version=head.catalog_version,
                    bucket=handoff.bucket,
                    queue_wait_s=t_admit - flight.t_enq,
                    compute_s=now - t_admit,
                    total_s=now - flight.t_enq,
                    request_id=span_ctx[0] if span_ctx is not None else None,
                    replica_id=self.replica_id,
                    prefill_worker_id=handoff.prefill_worker_id,
                    decode_worker_id=self.worker_id,
                )
            except Exception as e:  # noqa: BLE001 — one bad slot, not the loop
                self._log.exception(
                    f"disagg: finalize failed on worker {self.worker_id}"
                )
                if not flight.fut.done():
                    flight.fut.set_exception(e)
                self.metrics.record_failure(1)
            else:
                self.metrics.record_response(
                    resp.queue_wait_s, resp.compute_s, resp.total_s,
                    head=head.name,
                )
                if span_ctx is not None:
                    tid, sid, parent = span_ctx
                    t_final = time.monotonic()
                    ident = self._decode_span_ident()
                    self.tracer.record_span(
                        "finalize", tid, now, t_final, parent_id=sid,
                        **ident,
                    )
                    # The residency umbrella: admit -> evict, parenting
                    # every decode/spec step span this slot recorded.
                    self.tracer.record_span(
                        "slot_residency", tid, t_admit, t_final,
                        span_id=sid, parent_id=parent, slot=int(slot),
                        **ident,
                    )
                if not flight.fut.done():
                    flight.fut.set_result(resp)
            self.pool.evict(int(slot))
            self.active[slot] = False
            self.entries[slot] = None
            self.metrics.record_evict(1)

    # -- failure / teardown --------------------------------------------------

    def kill(self) -> list[Flight]:
        """SIGKILL-style death: mark dead, return the flights whose KV
        died with this worker (active slots), and release the emulated
        device resources so the shared bank accounts clean — on a real
        remote host the pages die with the process; here the allocator
        is shared and must not leak the casualty's refs."""
        self.dead = True
        stranded = []
        for slot in np.nonzero(self.active)[0]:
            flight, _handoff, t_admit, span_ctx = self.entries[slot]
            if not flight.fut.done():
                stranded.append(flight)
            if span_ctx is not None:
                # Close the residency span typed: the trace shows WHERE
                # the request was when its worker died, and the reroute
                # span the front records next stays in the same tree.
                tid, sid, parent = span_ctx
                self.tracer.record_span(
                    "slot_residency", tid, t_admit, time.monotonic(),
                    span_id=sid, parent_id=parent, slot=int(slot),
                    outcome="worker_killed", **self._decode_span_ident(),
                )
            self.pool.evict(int(slot))
            self.active[slot] = False
            self.entries[slot] = None
        # The emulated device dies with the worker: drop the scratch
        # reservation's refs too, or the shared bank would leak the
        # casualty's pinned pages forever.
        self.pool.release_scratch()
        return stranded

    def stats(self) -> dict:
        return {
            "slots_active": self.pool.active_slot_count,
            "slots_total": self.pool.cfg.max_slots,
            "occupancy": self.occupancy(),
            "headroom": self.headroom(),
            "admitted": self.admitted,
            "decode_steps": self.decode_steps,
            "scratch_pages": self.pool.scratch_page_count,
            "warmup_compiles": self.warmup_compiles,
            "recompilations": self.recompilations,
            "hbm": self.memory.summary(budget_bytes=self._hbm_budget),
        }
