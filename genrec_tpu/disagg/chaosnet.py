"""Deterministic network-fault injection for the socket serving tier.

The cross-host hop (disagg/net.py) is the availability-critical edge of
disaggregated serving, and "the network is reliable" is the first
fallacy. This module makes every classic wire failure injectable at the
FRAME boundary — the exact unit net.py reasons in — without touching a
production code path: `maybe_wrap(sock, role)` is a no-op (one module
attribute read, via `core.chaos.active()`) unless a `ChaosPlan` with
``net_faults`` is installed, so the wrapped code is the code that
serves traffic.

Fault kinds (``core.chaos.NetFault``), by side:

- ``latency``    (send/recv): sleep ``delay_s`` before the frame moves.
- ``drop``       (send): the frame's bytes vanish — a one-way
  partition/blackhole. The sender is none the wiser; the receiver just
  never sees the frame. (Send-side only: TCP cannot lose bytes without
  killing the stream, so a receive-side "drop" has no real analogue.)
- ``corrupt``    (send/recv): flip seeded byte positions — a corrupt
  length prefix, header, meta or payload must all land as TYPED errors
  on the reader, never a hang or a silent mis-parse.
- ``truncate``   (send): ship a prefix of the frame, then hard-reset —
  the peer sees EOF mid-frame (`kill -9` between length and payload).
- ``slow_loris`` (send): dribble the frame in 64-byte chunks with
  ``delay_s`` between them — a stalling-but-alive peer, bounded by the
  receiver's per-chunk socket timeout.
- ``reset``      (send/recv): SO_LINGER-0 close — an RST instead of a
  FIN, the mid-conversation connection reset.
- ``hang``       (send/recv): sleep ``delay_s`` with the frame parked —
  the hung-but-connected peer the liveness deadline (not the death
  detector) must catch.

Scheduling is by per-endpoint, per-side FRAME INDEX (`at_frame` ..
`at_frame + n_frames`) and by CONNECTION ordinal (`at_conn` ..
`at_conn + n_conns`, counted per process+role across wraps, so a
reconnect is the next ordinal), and every probabilistic draw comes from
`random.Random(net_seed ^ role)` consumed in frame order — the same
plan + seed replays the same fault sequence byte-for-byte. A fault
windowed to `n_conns=1` fires on the first connection and leaves the
reconnect that recovers from it clean — which is what makes a
zero-lost-requests chaos schedule deterministic.

The wrapper exploits (and asserts) net.py's framing discipline: one
`sendall` call == one frame on the send side; on the receive side it
parses the length-prefixed framing itself, byte-accurately, so faults
arm exactly at frame boundaries no matter how recv chunks.
"""

from __future__ import annotations

import random
import socket as socket_mod
import struct
import time
from typing import Optional

from genrec_tpu.core import chaos
from genrec_tpu.core.chaos import ChaosPlan, NetFault

_LEN = struct.Struct(">Q")

#: Which fault kinds make sense on which side (see module docstring).
KINDS_BY_SIDE = {
    "send": frozenset(
        {"latency", "drop", "corrupt", "truncate", "slow_loris", "reset",
         "hang"}),
    "recv": frozenset({"latency", "corrupt", "reset", "hang"}),
}

_ROLE_SALT = {"front": 0x66, "host": 0x68}

# Per-process connection ordinals, one counter per role: every wrap —
# initial connect, reconnect, newly accepted front — takes the next
# index, which `NetFault.at_conn`/`n_conns` windows match against.
_conn_counts: dict[str, int] = {}


def reset_conn_counts() -> None:
    """Test hook: restart the per-role connection ordinals."""
    _conn_counts.clear()


class ChaosInjectionError(ConnectionResetError):
    """The typed face of an injected reset/truncation on the INJECTING
    side (the peer sees a plain RST/EOF). A ConnectionResetError
    subclass, so every existing `except (OSError, ConnectionError)`
    handler treats it exactly like the real fault it simulates."""


def validate_faults(faults) -> None:
    for f in faults:
        if f.side not in KINDS_BY_SIDE:
            raise ValueError(f"NetFault side {f.side!r} not send/recv")
        if f.kind not in KINDS_BY_SIDE[f.side]:
            raise ValueError(
                f"NetFault kind {f.kind!r} not injectable on the "
                f"{f.side!r} side (have {sorted(KINDS_BY_SIDE[f.side])})"
            )
        if f.role not in ("front", "host", "*"):
            raise ValueError(f"NetFault role {f.role!r} not front/host/*")


class ChaosSocket:
    """A socket proxy applying the plan's schedule at frame boundaries.

    Everything not intercepted (fileno/settimeout/setsockopt/close/...)
    delegates to the wrapped socket, so select() and the existing
    timeout discipline see the real fd."""

    def __init__(self, sock, role: str, plan: ChaosPlan,
                 conn_idx: int = 0):
        validate_faults(plan.net_faults)
        self._sock = sock
        self.role = role
        self.conn_idx = conn_idx
        self._faults = [
            f for f in plan.net_faults
            if f.role in ("*", role)
            and (f.n_conns == 0
                 or f.at_conn <= conn_idx < f.at_conn + f.n_conns)
        ]
        self._rng = random.Random(
            int(plan.net_seed) ^ _ROLE_SALT.get(role, 0))
        self._tx_idx = 0
        self._rx_idx = 0
        # Receive-side frame parser: bytes of length prefix still
        # outstanding, then body countdown (None = prefix phase).
        self._rx_len_buf = bytearray()
        self._rx_body_left: Optional[int] = None
        self._rx_active: list[NetFault] = []
        #: (side, frame_idx, kind) log of every fault fired — the
        #: determinism pin reads this.
        self.applied: list[tuple[str, int, str]] = []

    # -- schedule ------------------------------------------------------------

    def _match(self, side: str, idx: int) -> list[NetFault]:
        out = []
        for f in self._faults:
            if f.side != side:
                continue
            if not (f.at_frame <= idx < f.at_frame + f.n_frames):
                continue
            # One seeded draw per in-window frame, in frame order:
            # the consumption sequence is what makes replays exact.
            if f.p < 1.0 and self._rng.random() >= f.p:
                continue
            out.append(f)
            self.applied.append((side, idx, f.kind))
        return out

    def _flip(self, data: bytes, n_flips: int = 3) -> bytes:
        buf = bytearray(data)
        for _ in range(min(n_flips, len(buf))):
            pos = self._rng.randrange(len(buf))
            buf[pos] ^= 1 << self._rng.randrange(8)
        return bytes(buf)

    def _hard_close(self) -> None:
        # RST, not FIN: linger-0 close aborts the connection, which is
        # what a yanked cable / dead NAT entry looks like to the peer.
        try:
            self._sock.setsockopt(
                socket_mod.SOL_SOCKET, socket_mod.SO_LINGER,
                struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    # -- send side (one sendall == one frame) --------------------------------

    def sendall(self, data) -> None:
        idx = self._tx_idx
        self._tx_idx += 1
        for f in self._match("send", idx):
            if f.kind in ("latency", "hang"):
                time.sleep(f.delay_s)
            elif f.kind == "drop":
                return  # blackhole: the frame never existed on the wire
            elif f.kind == "corrupt":
                data = self._flip(bytes(data))
            elif f.kind == "truncate":
                self._sock.sendall(bytes(data)[: max(1, len(data) // 2)])
                self._hard_close()
                raise ChaosInjectionError(
                    "chaosnet: injected mid-frame truncation")
            elif f.kind == "reset":
                self._hard_close()
                raise ChaosInjectionError(
                    "chaosnet: injected connection reset")
            elif f.kind == "slow_loris":
                buf = bytes(data)
                for i in range(0, len(buf), 64):
                    self._sock.sendall(buf[i:i + 64])
                    time.sleep(f.delay_s)
                return
        self._sock.sendall(data)

    # -- recv side (length-prefix parser finds the boundaries) ---------------

    def recv(self, n: int) -> bytes:
        if self._rx_body_left is None and not self._rx_len_buf:
            # About to deliver the first byte of a NEW frame.
            self._rx_active = self._match("recv", self._rx_idx)
            for f in self._rx_active:
                if f.kind in ("latency", "hang"):
                    time.sleep(f.delay_s)
                elif f.kind == "reset":
                    self._hard_close()
                    raise ChaosInjectionError(
                        "chaosnet: injected connection reset")
        data = self._sock.recv(n)
        if not data:
            return data
        self._advance_rx(data)
        if any(f.kind == "corrupt" for f in self._rx_active):
            data = self._flip(data)
        return data

    def _advance_rx(self, data: bytes) -> None:
        # Walk the UNCORRUPTED bytes so our own parser never desyncs
        # (the reader above us is welcome to — that is the test).
        i = 0
        while i < len(data):
            if self._rx_body_left is None:
                take = min(_LEN.size - len(self._rx_len_buf), len(data) - i)
                self._rx_len_buf += data[i:i + take]
                i += take
                if len(self._rx_len_buf) == _LEN.size:
                    (self._rx_body_left,) = _LEN.unpack(
                        bytes(self._rx_len_buf))
                    self._rx_len_buf.clear()
            else:
                take = min(self._rx_body_left, len(data) - i)
                self._rx_body_left -= take
                i += take
            if self._rx_body_left == 0:
                self._rx_body_left = None
                self._rx_idx += 1
                self._rx_active = []

    # -- passthrough ---------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self._sock, name)


def maybe_wrap(sock, role: str):
    """The production hook: wrap ``sock`` when the active `ChaosPlan`
    schedules network faults, else hand it back untouched. One module
    attribute read on the no-chaos path — same bar as every other
    chaos hook."""
    plan = chaos.active()
    if plan is None or not plan.net_faults:
        return sock
    idx = _conn_counts.get(role, 0)
    _conn_counts[role] = idx + 1
    return ChaosSocket(sock, role, plan, conn_idx=idx)
