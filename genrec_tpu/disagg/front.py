"""DisaggFront: the engine's `submit() -> Future` surface over
role-specialized prefill/decode worker pools.

Request path: `submit` routes to the least-loaded *prefill worker* of
the request's head (prefill saturates on queue depth); the completed
prefill emits a typed `KVHandoff` which the front routes to the decode
worker with the most free slots (decode saturates on slot occupancy);
the decode worker's continuous-batching loop resolves the caller's
future with full provenance (`Response.prefill_worker_id` /
`decode_worker_id` beside replica/params/catalog).

The co-located `ServingEngine` stays the default; disagg is opt-in per
head — a `DisaggFront` serves paged-capable heads only, and a deployment
mixes fronts and engines per head. The front duck-types the engine
surface (`start/stop/submit/stats()["headroom"]/metrics.warmup_compiles`
/`replica_id`), so a `fleet.FleetRouter` can route over N disagg fronts
exactly as it routes over N engines, while `fleet.Autoscaler` instances
scale the two roles INDEPENDENTLY through `role_pool(head, role)` —
each role pool speaks the router protocol the autoscaler drives
(`scale_signal`/`add_replica`/`remove_replica`).

Failure discipline (the fleet front's, one level down): a decode
worker's SIGKILL-style death strands the flights whose KV died with it —
each is re-submitted typed and AT MOST ONCE back through a surviving
prefill/decode pair (the KV must be re-encoded; a surviving prefill
worker's prefix cache usually makes that re-encode warm), and a second
loss fails `WorkerLostError`, never silence. Handoff validation failures
are typed `HandoffRefusedError` refusals. Drain completes in-flight
handoffs: queued requests prefill, pending handoffs land, decode slots
finish, and the pools on BOTH sides account clean.

Execution model: one runtime thread cooperatively schedules every
worker (prefill pass -> handoff delivery -> one decode step per worker)
— the engine's single-writer pool discipline held across the split, so
the in-process front is a CONTROL-PLANE of the disaggregated system;
compute overlap between roles arrives with the cross-host transport,
which slots in behind `KVTransport` without touching this file.
``start(run_loop=False)`` + `pump_once()` exposes the same scheduling
deterministically for tests and the chaos harness.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

from genrec_tpu.disagg.handoff import (
    HandoffRefusedError,
    WorkerLostError,
)
from genrec_tpu.disagg.transport import (
    InProcessTransport,
    KVTransport,
    SerializingTransport,
)
from genrec_tpu.disagg.workers import DecodeWorker, Flight, PrefillWorker
from genrec_tpu.obs.flight_recorder import get_flight_recorder
from genrec_tpu.obs.slo import SLOMonitor, SLOTarget
from genrec_tpu.obs.spans import NULL_TRACER, SpanTracer, TraceContext
from genrec_tpu.serving.buckets import BucketLadder, default_ladder
from genrec_tpu.serving.kv_pool import KVPagePool, PagedConfig
from genrec_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from genrec_tpu.serving.types import (
    DrainingError,
    OverloadError,
    Request,
    UnknownHeadError,
    normalize_spec_config,
)


class _HeadGroup:
    """One head's role pools + in-flight handoffs."""

    __slots__ = ("head", "bank", "transport", "prefill", "decode",
                 "pending", "seq", "spec_topology")

    def __init__(self, head, bank, transport, spec_topology=None):
        self.head = head
        self.bank: Optional[KVPagePool] = bank
        self.transport: KVTransport = transport
        self.prefill: list[PrefillWorker] = []
        self.decode: list[DecodeWorker] = []
        # (flight, handoff, t_sent): sent but not yet admitted — routed
        # to a concrete decode worker only when one has a free slot, so
        # a kill in between strands nothing that is still re-routable.
        self.pending: collections.deque = collections.deque()
        self.seq = {"prefill": 0, "decode": 0}
        # ops.spec_tree.TreeTopology when this head speculates: shared
        # by every decode worker in the group (one topology per rung —
        # the check_spec_hlo pin, held across the split).
        self.spec_topology = spec_topology


class _RolePool:
    """`fleet.Autoscaler`-compatible view of one (head, role) pool:
    scale_signal/add_replica/remove_replica over WORKERS instead of
    engine replicas — the two roles scale independently, each on its own
    saturation signal."""

    def __init__(self, front: "DisaggFront", head: str, role: str):
        self._front = front
        self.head = head
        self.role = role

    def scale_signal(self) -> dict:
        return self._front._role_signal(self.head, self.role)

    def add_replica(self) -> str:
        return self._front._add_worker(self.head, self.role)

    def remove_replica(self, worker_id: str, timeout: float = 60.0) -> dict:
        return self._front._remove_worker(self.head, self.role, worker_id,
                                          timeout)


class DisaggFront:
    def __init__(
        self,
        heads: Sequence,
        params,
        *,
        ladder: Optional[BucketLadder] = None,
        max_batch: int = 8,
        max_wait_ms: float = 4.0,
        n_prefill: int = 1,
        n_decode: int = 1,
        transport: str = "inprocess",
        workers: Optional[Sequence[str]] = None,
        standby_workers: Optional[Sequence[str]] = None,
        remote_net: Optional[dict] = None,
        paged_config: Optional[PagedConfig] = None,
        bank_num_pages: Optional[int] = None,
        prefix_cache: bool = True,
        prefix_cache_entries: int = 4096,
        prefill_hbm_budget_bytes: Optional[int] = None,
        decode_hbm_budget_bytes: Optional[int] = None,
        slo_targets: Optional[dict] = None,
        slo_poll_secs: float = 0.05,
        params_step: Optional[int] = None,
        params_by_head: Optional[bool] = None,
        replica_id: Optional[str] = None,
        spec_decode=False,
        spec_fanout=8,
        mesh=None,
        model_axis: str = "model",
        tracer: Optional[SpanTracer] = None,
        handle_signals: bool = False,
        guard=None,
        logger: Optional[logging.Logger] = None,
    ):
        self._heads = {h.name: h for h in heads}
        if len(self._heads) != len(heads):
            raise ValueError("duplicate head names")
        for h in heads:
            if not getattr(h, "supports_paged", False):
                raise ValueError(
                    f"head {h.name!r} has no paged decode protocol — "
                    "disagg is opt-in per head; serve it on the "
                    "co-located ServingEngine instead"
                )
        self._params = params
        self._params_by_head = (
            params_by_head if params_by_head is not None
            else len(self._heads) > 1
        )
        if self._params_by_head:
            missing = [n for n in self._heads if n not in params]
            if missing:
                raise ValueError(f"params missing head subtrees: {missing}")
        self._step = params_step
        self._ladder = ladder or default_ladder(max_batch=max_batch)
        self._max_batch = max_batch
        self._max_wait_s = max_wait_ms / 1e3
        if n_prefill < 1 or n_decode < 1:
            raise ValueError("need at least one worker per role")
        self._n_prefill = n_prefill
        self._n_decode = n_decode
        if transport not in ("inprocess", "serializing", "socket"):
            raise ValueError(
                f"unknown transport {transport!r}: "
                "'inprocess' (zero-copy shared page bank), "
                "'serializing' (host-roundtrip wire) or "
                "'socket' (cross-process decode hosts)"
            )
        if transport == "socket":
            if not workers:
                raise ValueError(
                    "transport='socket' needs workers=[\"host:port\", ...] "
                    "— the decode-host processes this front serves "
                    "through (spawn_decode_host returns the address)"
                )
        elif workers:
            raise ValueError(
                f"workers= is the socket tier's knob; the {transport!r} "
                "transport builds its decode workers in-process "
                "(n_decode=)"
            )
        self._transport_kind = transport
        self._remote_addrs = list(workers or ())
        # Unconnected decode-host addresses scale-out may consume
        # (_add_worker on the socket tier attaches one per call).
        self._standby_addrs = list(standby_workers or ())
        # Socket-tier resilience knobs forwarded verbatim to every
        # RemoteDecodeWorker this front builds (liveness_timeout,
        # reconnect_max, reconnect_base, reconnect_cap, reconnect_seed).
        if remote_net and transport != "socket":
            raise ValueError("remote_net= is the socket tier's knob")
        self._remote_net = dict(remote_net or ())
        self._paged_config = paged_config
        self._bank_num_pages = bank_num_pages
        self._prefix_cache = bool(prefix_cache)
        self._prefix_cache_entries = int(prefix_cache_entries)
        self._prefill_budget = prefill_hbm_budget_bytes
        self._decode_budget = decode_hbm_budget_bytes
        self.replica_id = replica_id
        # Speculative decode on the decode POOL (the engine's exact
        # opt-in surface, per front): True/False, or a set of head
        # names. The decode workers compile the tree-verify rung
        # ladder; prefill workers are untouched beyond the drafter-hint
        # state enable_spec_drafting() adds to the head.
        self._spec_decode, self._spec_fanout = normalize_spec_config(
            spec_decode, spec_fanout, self._heads
        )
        # Tensor-parallel serving operands (the engine's mesh= knob, per
        # front): params shard by serve_rules, owned pools/banks shard
        # their page banks over the head axis. Socket-tier decode HOSTS
        # place their own mesh (factory mesh_shape) — this knob covers
        # the front's prefill side and the in-process tiers.
        self._mesh = mesh
        self._model_axis = str(model_axis)
        self._handle_signals = handle_signals
        self._guard = guard
        self._log = logger or logging.getLogger("genrec_tpu")
        self._flight = get_flight_recorder().scoped(
            "disagg_front", replica_id=lambda: self.replica_id
        )
        # Request lineage: adopt an incoming Request.trace (a fleet
        # router upstream) or mint one here — either way every worker
        # span parents under this front's per-request span. Workers
        # share THIS tracer (one span-id space per process).
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = ServingMetrics()
        # Role-level SLO guard: {"prefill": SLOTarget, "decode":
        # SLOTarget} applied per head; the monitor keys on
        # "<head>/<role>" and submit sheds when EITHER role of the
        # request's head is shedding (a saturated decode pool must push
        # back at admission, not queue unboundedly at prefill).
        if slo_targets is None:
            self._slo = None
        else:
            unknown = [r for r in slo_targets if r not in ("prefill",
                                                           "decode")]
            if unknown:
                raise ValueError(
                    f"slo_targets keys must be roles, got {unknown}")
            targets = {
                f"{name}/{role}": t
                for name in self._heads
                for role, t in slo_targets.items()
                if isinstance(t, SLOTarget)
            }
            self._slo = SLOMonitor(targets, flight=self._flight)
        self._slo_poll_secs = float(slo_poll_secs)
        self._slo_next_poll = 0.0
        self._groups: dict[str, _HeadGroup] = {}
        # Queue lock + wake condition (submit threads <-> runtime
        # thread) and the coarse runtime lock serializing pump
        # iterations with operator verbs (kill/add/remove).
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._runtime = threading.RLock()
        self._counters = {
            "handoffs_sent": 0,
            "handoffs_admitted": 0,
            "handoffs_refused": 0,
            "handoffs_resubmitted": 0,
            "transfer_bytes": 0,
            "decode_worker_deaths": 0,
            "prefill_worker_deaths": 0,
            "degraded_entered": 0,
            "degraded_exited": 0,
        }
        # Heads whose decode pool currently has ZERO live capacity
        # (socket tier: every remote peer unreachable). While a head is
        # degraded, submit sheds with the recoverable OverloadError
        # instead of queueing work that can only hang; pump_once exits
        # the head the moment a worker (reconnected or promoted
        # standby) is live again.
        self._degraded: set[str] = set()
        self.transfer = LatencyHistogram()
        self._draining = False
        self._drained = threading.Event()
        self._batcher: Optional[threading.Thread] = None
        self._started = False

    # -- construction helpers ------------------------------------------------

    def _select(self, head, params):
        return params[head.name] if self._params_by_head else params

    def _default_config(self, head) -> PagedConfig:
        page_size = 16
        max_kv = head.paged_kv_tokens(10**9, self._ladder.history_buckets[-1])
        return PagedConfig(
            max_slots=4 * self._max_batch,
            page_size=page_size,
            pages_per_slot=-(-max_kv // page_size),
        )

    def _spec_topology_for(self, head, cfg: PagedConfig):
        """One TreeTopology per spec-enabled head group (every decode
        worker's rungs compile the same tree). Calling
        ``enable_spec_drafting()`` HERE — before any worker builds
        state or compiles prefill — lets the head extend its slot
        state/prefill with drafter hints, exactly the engine's
        construction order."""
        want = (
            head.name in self._spec_decode
            if isinstance(self._spec_decode, frozenset)
            else bool(self._spec_decode)
        )
        if not (want and getattr(head, "supports_spec", False)
                and head.spec_depth >= 1):
            return None
        from genrec_tpu.ops.spec_tree import TreeTopology

        head.enable_spec_drafting()
        return TreeTopology(head.top_k, self._spec_fanout, head.spec_depth)

    @staticmethod
    def _scratch_pages_per_worker(topo, cfg: PagedConfig) -> int:
        if topo is None:
            return 0
        return cfg.max_slots * (-(-topo.n_nodes // cfg.page_size))

    def _build_group(self, head) -> _HeadGroup:
        cfg = self._paged_config or self._default_config(head)
        max_kv = head.paged_kv_tokens(10**9, self._ladder.history_buckets[-1])
        if cfg.max_kv_tokens < max_kv:
            raise ValueError(
                f"paged config holds {cfg.max_kv_tokens} KV tokens/slot "
                f"but head {head.name!r} needs {max_kv} at the largest "
                "history bucket"
            )
        topo = self._spec_topology_for(head, cfg)
        n_layers, n_heads, head_dim, dtype = head.paged_layout()
        if self._transport_kind == "inprocess":
            # One shared page bank per head: decode workers are slot
            # VIEWS over it, prefill writes raw runs into it — the
            # zero-copy handoff substrate. Sized for every decode slot
            # plus in-flight prefill staging (retained prefix pages ride
            # inside and reclaim under pressure), EXTENDED by each
            # speculative decode worker's scratch reservation so
            # speculation never eats admission capacity.
            bank_pages = (self._bank_num_pages or (
                1 + cfg.pages_per_slot
                * (self._n_decode * cfg.max_slots + 2 * self._max_batch)
            )) + self._n_decode * self._scratch_pages_per_worker(topo, cfg)
            bank_cfg = PagedConfig(
                max_slots=1, page_size=cfg.page_size,
                pages_per_slot=cfg.pages_per_slot, num_pages=bank_pages,
                kv_dtype=cfg.kv_dtype,
            )
            bank = KVPagePool(bank_cfg, n_layers, n_heads, head_dim, dtype)
            if self._mesh is not None:
                from genrec_tpu.parallel.shardings import kv_pool_sharding

                place = kv_pool_sharding(self._mesh, n_heads,
                                         self._model_axis)
                if place is not None:
                    bank.place(place)
            return _HeadGroup(head, bank, InProcessTransport(bank),
                              spec_topology=topo)
        if self._transport_kind == "socket":
            from genrec_tpu.disagg.net import SocketTransport

            return _HeadGroup(head, None, SocketTransport(),
                              spec_topology=topo)
        return _HeadGroup(head, None, SerializingTransport(),
                          spec_topology=topo)

    def _make_prefill(self, group: _HeadGroup) -> PrefillWorker:
        head = group.head
        wid = f"{head.name}:p{group.seq['prefill']}"
        group.seq["prefill"] += 1
        cfg = self._paged_config or self._default_config(head)
        if group.bank is not None:
            pool, owns = group.bank, False
        else:
            n_layers, n_heads, head_dim, dtype = head.paged_layout()
            staging_cfg = PagedConfig(
                max_slots=1, page_size=cfg.page_size,
                pages_per_slot=cfg.pages_per_slot,
                num_pages=1 + cfg.pages_per_slot * 3 * self._max_batch,
                kv_dtype=cfg.kv_dtype,
            )
            pool = KVPagePool(staging_cfg, n_layers, n_heads, head_dim, dtype)
            owns = True
        return PrefillWorker(
            wid, head, self._select(head, self._params),
            ladder=self._ladder, transport=group.transport, pool=pool,
            owns_pool=owns, max_batch=self._max_batch,
            max_wait_s=self._max_wait_s, metrics=self.metrics,
            flight_recorder=self._flight.scoped("prefill_worker",
                                                worker_id=wid),
            params_step=self._step,
            prefix_cache=self._prefix_cache,
            prefix_cache_entries=self._prefix_cache_entries,
            hbm_budget_bytes=self._prefill_budget,
            tracer=self._tracer,
            mesh=self._mesh, model_axis=self._model_axis,
            logger=self._log,
        )

    def _make_decode(self, group: _HeadGroup) -> DecodeWorker:
        head = group.head
        wid = f"{head.name}:d{group.seq['decode']}"
        group.seq["decode"] += 1
        cfg = self._paged_config or self._default_config(head)
        scratch = self._scratch_pages_per_worker(group.spec_topology, cfg)
        n_layers, n_heads, head_dim, dtype = head.paged_layout()
        if group.bank is not None:
            view_cfg = PagedConfig(
                max_slots=cfg.max_slots, page_size=cfg.page_size,
                pages_per_slot=cfg.pages_per_slot,
                num_pages=group.bank.cfg.num_pages,
                kv_dtype=cfg.kv_dtype,
            )
            pool = KVPagePool(view_cfg, n_layers, n_heads, head_dim, dtype,
                              bank=group.bank)
            owns = False
        else:
            # Serializing tier: each decode worker owns its pool —
            # extend it by the scratch reservation (an explicit
            # paged_config keeps its admission capacity; the ledger
            # sees the real total — the engine's discipline).
            if scratch:
                cfg = dataclasses.replace(
                    cfg, num_pages=cfg.num_pages + scratch
                )
            pool = KVPagePool(cfg, n_layers, n_heads, head_dim, dtype)
            owns = True
        return DecodeWorker(
            wid, head, self._select(head, self._params),
            transport=group.transport, pool=pool, owns_pool=owns,
            ladder=self._ladder, metrics=self.metrics,
            flight_recorder=self._flight.scoped("decode_worker",
                                                worker_id=wid),
            slot_floor=min(self._max_batch, cfg.max_slots),
            params_step=self._step, replica_id=self.replica_id,
            hbm_budget_bytes=self._decode_budget,
            spec_topology=group.spec_topology,
            spec_fanout=self._spec_fanout,
            tracer=self._tracer,
            mesh=self._mesh, model_axis=self._model_axis,
            logger=self._log,
        )

    def _make_remote_decode(self, addr: str):
        """One connected `RemoteDecodeWorker` proxy for a decode-host
        process (socket tier). The host accepts exactly ONE connection,
        so the proxy connects once and is then routed to its head's
        group by the identity it announced in its HELLO — a dead
        address or an unknown head refuses at attach time, typed, never
        at delivery time."""
        from genrec_tpu.disagg.net import RemoteDecodeWorker

        # The group's transport carries the tier's wire counters; until
        # the HELLO names the head, connect through a throwaway one and
        # swap after routing (warmup only touches connect counters).
        w = RemoteDecodeWorker(
            addr, transport=next(
                g.transport for g in self._groups.values()
            ), metrics=self.metrics, counters=self._counters,
            flight_recorder=self._flight.scoped("decode_worker",
                                                worker_id=addr),
            replica_id=self.replica_id, tracer=self._tracer,
            logger=self._log, **self._remote_net,
        )
        w.warmup()
        head_name = w.identity["head"]
        group = self._groups.get(head_name)
        if group is None:
            w.kill()
            raise UnknownHeadError(
                f"decode host {addr} serves head {head_name!r} but "
                f"this front only has {sorted(self._groups)}"
            )
        w.worker_id = f"{head_name}:d{group.seq['decode']}"
        group.seq["decode"] += 1
        w.transport = group.transport
        w._flight = self._flight.scoped("decode_worker",
                                        worker_id=w.worker_id)
        group.decode.append(w)
        return w

    def _connect_remote_decodes(self) -> None:
        """Socket tier: attach every configured decode-host address."""
        for addr in self._remote_addrs:
            self._make_remote_decode(addr)

    # -- lifecycle -----------------------------------------------------------

    def start(self, run_loop: bool = True) -> "DisaggFront":
        if self._started:
            raise RuntimeError("front already started")
        for head in self._heads.values():
            head.on_params(self._select(head, self._params))
        t0 = time.monotonic()
        for head in self._heads.values():
            group = self._build_group(head)
            for _ in range(self._n_prefill):
                group.prefill.append(self._make_prefill(group))
            if self._transport_kind != "socket":
                for _ in range(self._n_decode):
                    group.decode.append(self._make_decode(group))
            self._groups[head.name] = group
        if self._transport_kind == "socket":
            # Decode pools live in their own processes: attach one
            # proxy per configured host (connect + HELLO; the host
            # warmed its grid before accepting).
            self._connect_remote_decodes()
            for name, g in self._groups.items():
                if not g.decode:
                    raise WorkerLostError(
                        f"no decode host connected for head {name!r} — "
                        "every head needs at least one workers= address"
                    )
        workers = [w for g in self._groups.values()
                   for w in g.prefill + g.decode]
        for w in workers:
            # Operands-only budget pass over EVERY worker first: an
            # impossible budget on any role refuses before the front
            # pays a single compile (prefill warms before decode below,
            # so warmup()'s own early check alone would not cover a
            # decode-side refusal).
            w._ledger(operands_only=True)
        for w in workers:
            w.warmup()  # HBMBudgetError refusal propagates
        self.metrics.warmup_compiles = sum(
            w.warmup_compiles
            for g in self._groups.values() for w in g.prefill + g.decode
        )
        self.metrics.mark_warm()
        if self._guard is None and self._handle_signals:
            from genrec_tpu.core.preemption import PreemptionGuard

            self._guard = PreemptionGuard(self._log)
        self._started = True
        self._flight.record(
            "disagg_started", heads=sorted(self._heads),
            transport=self._transport_kind,
            prefill_workers=sum(len(g.prefill)
                                for g in self._groups.values()),
            decode_workers=sum(len(g.decode)
                               for g in self._groups.values()),
            warmup_compiles=self.metrics.warmup_compiles,
            replica_id=self.replica_id,
        )
        self._log.info(
            f"disagg: front up ({self._transport_kind} transport, "
            f"{self._n_prefill} prefill + {self._n_decode} decode "
            f"workers/head, {self.metrics.warmup_compiles} warmup "
            f"executables in {time.monotonic() - t0:.1f}s)"
        )
        if run_loop:
            self._batcher = threading.Thread(
                target=self._run_loop, name="disagg-runtime", daemon=True
            )
            self._batcher.start()
        return self

    def stop(self, timeout: float = 60.0) -> dict:
        """Drain: queued requests prefill, in-flight handoffs land,
        decode slots finish; new submissions get the typed error.
        Idempotent; returns the final stats snapshot."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        if self._batcher is not None:
            self._batcher.join(timeout)
        else:
            # Loop-less front (run_loop=False): pump the drain here.
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                progressed = self.pump_once()
                if self._check_drained():
                    break
                if not progressed:
                    time.sleep(1e-3)
            if not self._drained.is_set():
                self._finish_drain()
        if self._guard is not None:
            self._guard.close()
        return self.stats()

    def join(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def params_step(self) -> Optional[int]:
        return self._step

    def set_tracer(self, tracer: Optional[SpanTracer]) -> None:
        """Swap lineage tracing live, front-wide: the front's own spans
        and every worker's. The workers read their ``tracer`` attribute
        per call, so this is a plain reference swap (the engine's
        set_tracer contract, one level down)."""
        self._tracer = tracer if tracer is not None else NULL_TRACER
        with self._runtime:
            for group in self._groups.values():
                for w in group.prefill + group.decode:
                    w.tracer = self._tracer

    # -- request path --------------------------------------------------------

    def submit(self, req: Request) -> Future:
        head = self._heads.get(req.head)
        if head is None:
            raise UnknownHeadError(
                f"unknown head {req.head!r}; have {sorted(self._heads)}"
            )
        head.validate(req)
        flight = Flight(req)
        with self._lock:
            if self._draining:
                self.metrics.record_reject(req.head)
                raise DrainingError(
                    "disagg front is draining; request rejected — fail "
                    "over to another replica"
                )
            if self._slo is not None and (
                self._slo.is_shedding(f"{req.head}/prefill")
                or self._slo.is_shedding(f"{req.head}/decode")
            ):
                self.metrics.record_overload(req.head)
                raise OverloadError(
                    f"head {req.head!r} disagg pools are load-shedding; "
                    "back off and retry or fail over"
                )
            if req.head in self._degraded:
                # Degraded mode: every remote decode peer is currently
                # unreachable. Shed at admission with the recoverable
                # error rather than accept work that can only pile up
                # behind reconnect — the caller (or FleetRouter) backs
                # off / fails over, and the head exits degraded the
                # moment a peer is live again.
                self.metrics.record_overload(req.head)
                raise OverloadError(
                    f"head {req.head!r} is in degraded mode (no "
                    "reachable decode peers); back off and retry or "
                    "fail over"
                )
            self._attach_trace(flight)
            try:
                self._enqueue_locked(flight)
            except WorkerLostError as e:
                # Zero live prefill workers: to a FLEET caller this
                # replica is saturated-unusable, not broken — raise the
                # recoverable error FleetRouter fails over on
                # (WorkerLostError would propagate through the router as
                # a caller bug and skip the surviving replicas).
                self.metrics.record_overload(req.head)
                raise OverloadError(
                    f"head {req.head!r} has no live prefill workers on "
                    f"this front; fail over ({e})"
                ) from e
            self._work.notify()
        self.metrics.record_submit(head=req.head)
        return flight.fut

    def _attach_trace(self, flight: Flight) -> None:
        """Adopt the request's incoming lineage (a fleet router above
        us) or mint it here, and pre-allocate this front's per-request
        span: the prefill worker's admission/prefill spans, the
        handoff's wire spans and the decode worker's residency span all
        parent onto it, and it is recorded — submit to future-resolve,
        reroutes included — when the caller's future settles."""
        req = flight.req
        ctx_in = req.trace
        tracer = self._tracer
        if not tracer.enabled:
            if ctx_in is not None:
                # Tracing off on this front but the request IS traced:
                # carry the id (Response.request_id provenance); span
                # recording no-ops downstream.
                flight.trace = ctx_in
            return
        tid = ctx_in.trace_id if ctx_in is not None else tracer.new_trace()
        parent = ctx_in.parent_span_id if ctx_in is not None else None
        origin = ctx_in.origin if ctx_in is not None else "disagg_front"
        fspan = tracer.allocate_span_id()
        flight.trace = TraceContext(tid, fspan, origin)
        t_sub = flight.t_enq
        ident = {"component": "disagg_front"}
        if self.replica_id is not None:
            ident["replica"] = self.replica_id

        def _record_request(f, tid=tid, fspan=fspan, parent=parent,
                            t_sub=t_sub, head_name=req.head,
                            origin=origin, ident=ident):
            try:
                outcome = "error" if f.exception() else "ok"
            except Exception:  # noqa: BLE001 — cancelled future
                outcome = "cancelled"
            tracer.record_span(
                "request", tid, t_sub, time.monotonic(), span_id=fspan,
                parent_id=parent, head=head_name, origin=origin,
                outcome=outcome, **ident,
            )

        flight.fut.add_done_callback(_record_request)

    def serve(self, req: Request, timeout: Optional[float] = 60.0):
        return self.submit(req).result(timeout)

    def _enqueue_locked(self, flight: Flight) -> None:
        """Route to the prefill worker with the shallowest queue (the
        prefill pool's saturation signal IS queue depth). Caller holds
        the queue lock."""
        group = self._groups[flight.req.head]
        live = [w for w in group.prefill if not w.dead and not w.draining]
        if not live:
            raise WorkerLostError(
                f"no live prefill workers for head {flight.req.head!r}"
            )
        min(live, key=lambda w: (len(w.queue), w.worker_id)).queue.append(
            flight
        )

    # -- the runtime loop ----------------------------------------------------

    def _run_loop(self) -> None:
        try:
            while True:
                try:
                    if (
                        self._guard is not None
                        and self._guard.fired
                        and not self._draining
                    ):
                        with self._lock:
                            self._draining = True
                        self._flight.record("disagg_drain_started",
                                            cause="signal")
                    progressed = self.pump_once()
                    if self._draining and self._check_drained():
                        break
                    if progressed:
                        continue
                    with self._lock:
                        busy = any(
                            w.queue for g in self._groups.values()
                            for w in g.prefill
                        ) or any(g.pending for g in self._groups.values())
                        self._work.wait(
                            timeout=max(self._max_wait_s / 4, 1e-3)
                            if busy else 0.05
                        )
                except Exception:  # noqa: BLE001 — the loop must survive
                    self._log.exception("disagg: runtime iteration failed")
        finally:
            self._finish_drain()

    def pump_once(self) -> bool:
        """One cooperative scheduling pass over every worker: prefill
        admission -> handoff delivery -> one decode step per worker.
        Deterministically callable when started with run_loop=False (the
        chaos tests single-step the pipeline through it)."""
        progressed = False
        with self._runtime:
            for group in self._groups.values():
                for pw in list(group.prefill):
                    if pw.dead:
                        continue
                    for fl, handoff in pw.pump(self._lock, self._draining):
                        self._counters["handoffs_sent"] += 1
                        self._flight.record(
                            "handoff_sent", head=group.head.name,
                            prefill_worker=handoff.prefill_worker_id,
                            n_tokens=handoff.n_tokens, warm=handoff.warm,
                            transfer_bytes=handoff.transfer_bytes,
                        )
                        group.pending.append((fl, handoff, time.monotonic()))
                        progressed = True
                progressed |= self._deliver(group)
                for dw in list(group.decode):
                    if dw.dead:
                        # A remote proxy marks ITSELF dead when its peer
                        # process drops (kill -9 included) — the pump
                        # reaps it here exactly like kill_decode_worker:
                        # re-submit every resident flight, typed and
                        # at-most-once. In-process workers only die via
                        # the kill verb, which already removed them.
                        if dw in group.decode:
                            self._reap_dead_decode(group, dw)
                            progressed = True
                        continue
                    progressed |= dw.step()
                    # A reconnect stranded this worker's pre-reconnect
                    # flights (the host orphaned them): re-submit each
                    # through prefill, at-most-once, exactly like the
                    # death path — but the worker itself stays live.
                    take = getattr(dw, "take_stranded", None)
                    if take is not None:
                        for fl in take():
                            self._resubmit(group, fl,
                                           from_worker=dw.worker_id)
                            progressed = True
                self._update_degraded(group)
            self._poll_slo()
        return progressed

    def _update_degraded(self, group: _HeadGroup) -> None:
        """Enter/exit the head's degraded mode on the socket tier: zero
        reachable decode peers in, first live peer out. Flight-evented
        both ways and visible in stats()["disagg"]["degraded_heads"]."""
        if self._transport_kind != "socket":
            return
        name = group.head.name
        live = any(
            not w.dead and not w.draining
            and not getattr(w, "reconnecting", False)
            for w in group.decode
        )
        if not live and name not in self._degraded:
            self._degraded.add(name)
            self._counters["degraded_entered"] += 1
            self._flight.record(
                "degraded_mode_entered", head=name,
                decode_workers=len(group.decode),
            )
            self._log.warning(
                f"disagg: head {name!r} entered degraded mode — no "
                "reachable decode peers; shedding at admission"
            )
        elif live and name in self._degraded:
            self._degraded.discard(name)
            self._counters["degraded_exited"] += 1
            self._flight.record("degraded_mode_exited", head=name)
            self._log.info(
                f"disagg: head {name!r} exited degraded mode — decode "
                "capacity restored"
            )

    def _reap_dead_decode(self, group: _HeadGroup, worker) -> None:
        """kill_decode_worker's body for a worker that died on its own
        (a lost decode-host peer): remove, strand, re-submit typed."""
        group.decode.remove(worker)
        stranded = worker.kill()
        group.transport.forget(worker.pool)
        self._counters["decode_worker_deaths"] += 1
        self._flight.record(
            "disagg_worker_dead", worker=worker.worker_id, role="decode",
            head=group.head.name, stranded=len(stranded),
            survivors=len(group.decode),
            peer=getattr(worker, "peer_addr", None),
        )
        self._log.warning(
            f"disagg: decode worker {worker.worker_id} "
            f"({getattr(worker, 'peer_addr', 'in-process')}) lost with "
            f"{len(stranded)} requests resident — re-submitting through "
            f"{len(group.decode)} survivors"
        )
        for fl in stranded:
            self._resubmit(group, fl, from_worker=worker.worker_id)

    def _deliver(self, group: _HeadGroup) -> bool:
        """Route pending handoffs onto decode workers with free slots
        (most-free-first — the decode pool's saturation signal is slot
        occupancy). A handoff with no admissible worker NOW stays
        pending; zero live decode workers is a typed failure."""
        progressed = False
        while group.pending:
            live = [w for w in group.decode
                    if not w.dead and not w.draining]
            if not live:
                fl, handoff, _t = group.pending.popleft()
                group.transport.release(handoff)
                if not fl.fut.done():
                    if self._transport_kind == "socket":
                        # Socket tier: dead peers are a NETWORK outcome
                        # (partition, crash) the fleet fails over on —
                        # shed recoverable, and enter degraded mode so
                        # subsequent submits shed at admission instead
                        # of burning a prefill first.
                        self._update_degraded(group)
                        self.metrics.record_overload(group.head.name)
                        fl.fut.set_exception(OverloadError(
                            f"head {group.head.name!r} has no reachable "
                            "decode peers (degraded mode); back off and "
                            "retry or fail over"
                        ))
                    else:
                        fl.fut.set_exception(WorkerLostError(
                            f"no live decode workers for head "
                            f"{group.head.name!r}; handoff dropped typed"
                        ))
                    self.metrics.record_failure(1)
                progressed = True
                continue
            target = max(live, key=lambda w: (w.free_slots, w.worker_id))
            if target.free_slots == 0:
                break  # every live worker full: deliver after evictions
            fl, handoff, t_sent = group.pending.popleft()
            if fl.fut.done():  # caller cancelled while in flight
                group.transport.release(handoff)
                continue
            tb = handoff.transfer_bytes
            t_adm0 = time.monotonic()
            try:
                target.validate(handoff)
                admitted = target.admit(fl, handoff)
            except Exception as e:  # noqa: BLE001 — any admit failure
                # Typed refusals AND unexpected admit errors take the
                # same exit: the flight was already popped from pending,
                # so anything escaping here would strand its future
                # unresolved (the caller hangs to its own timeout).
                if not isinstance(e, HandoffRefusedError):
                    self._log.exception(
                        f"disagg: handoff admit failed on "
                        f"{target.worker_id}"
                    )
                group.transport.release(handoff)
                self._counters["handoffs_refused"] += 1
                self._flight.record(
                    "handoff_refused", head=group.head.name,
                    prefill_worker=handoff.prefill_worker_id,
                    decode_worker=target.worker_id, reason=str(e),
                )
                if not fl.fut.done():
                    fl.fut.set_exception(e)
                self.metrics.record_failure(1)
                progressed = True
                continue
            if not admitted:
                group.pending.appendleft((fl, handoff, t_sent))
                break
            if fl.trace is not None and self._tracer.enabled:
                tr = fl.trace
                # The tail's two disagg-specific segments: time the
                # handoff sat waiting for a free decode slot, and the
                # receive side of the wire (unpack + scatter + bind).
                self._tracer.record_span(
                    "decode_slot_wait", tr.trace_id, t_sent, t_adm0,
                    parent_id=tr.parent_span_id, component="disagg_front",
                    worker=target.worker_id,
                )
                self._tracer.record_span(
                    "handoff_wire", tr.trace_id, t_adm0, time.monotonic(),
                    parent_id=tr.parent_span_id, side="admit",
                    transport=group.transport.name, transfer_bytes=tb,
                    component="decode_worker", worker=target.worker_id,
                    peer=getattr(target, "peer_addr", None),
                )
            self._counters["handoffs_admitted"] += 1
            self._counters["transfer_bytes"] += tb
            self.transfer.record(time.monotonic() - t_sent)
            self._flight.record(
                "handoff_admitted", head=group.head.name,
                prefill_worker=handoff.prefill_worker_id,
                decode_worker=target.worker_id,
                n_tokens=handoff.n_tokens, warm=handoff.warm,
                transfer_bytes=tb,
            )
            progressed = True
        return progressed

    def _check_drained(self) -> bool:
        with self._lock:
            queues_empty = all(
                not w.queue for g in self._groups.values()
                for w in g.prefill
            )
        return (
            queues_empty
            and all(not g.pending for g in self._groups.values())
            and all(dw.idle for g in self._groups.values()
                    for dw in g.decode if not dw.dead)
        )

    def _finish_drain(self) -> None:
        # Release every retained prefix page — and every speculative
        # scratch reservation — so the banks/pools account clean at
        # shutdown (pages released after drain — the check_disagg bar,
        # both sides; scratch_pages == 0 is the check_spec bar).
        with self._runtime:
            for group in self._groups.values():
                for pw in group.prefill:
                    pw.clear_prefix_cache("drain")
                for dw in group.decode:
                    n = dw.pool.release_scratch()
                    if n:
                        self._flight.record(
                            "spec_scratch_released", head=group.head.name,
                            worker_id=dw.worker_id, reason="drain", pages=n,
                        )
                    if hasattr(dw, "close"):
                        # Remote proxy: SHUTDOWN handshake drains the
                        # host process and closes both sockets clean.
                        dw.close()
        self._flight.record("disagg_stopped",
                            completed=self.metrics.completed)
        self._drained.set()

    # -- SLO guard -----------------------------------------------------------

    def _poll_slo(self) -> None:
        if self._slo is None:
            return
        now = time.monotonic()
        if now < self._slo_next_poll:
            return
        self._slo_next_poll = now + self._slo_poll_secs
        for name, group in self._groups.items():
            with self._lock:
                qdepth = sum(len(w.queue) for w in group.prefill)
            for role, depth, p99, deferred in (
                # Deferral is an ADMISSION-side phenomenon: feed the
                # per-head oom/submit counters to the prefill target so
                # SLOTarget.max_deferral_rate sheds a page-thrashing
                # pool (the engine's _poll_slo wiring, per role).
                ("prefill", qdepth, None,
                 self.metrics.oom_deferred_by_head[name]),
                ("decode", len(group.pending),
                 self.metrics.recent_p99_ms(
                     self._slo.targets.get(
                         f"{name}/decode",
                         SLOTarget(max_queue_depth=1)).window_s,
                     head=name)
                 if f"{name}/decode" in self._slo.targets else None, None),
            ):
                key = f"{name}/{role}"
                if key in self._slo.targets:
                    self._slo.observe(
                        key, p99_ms=p99, queue_depth=depth,
                        oom_deferred_total=deferred,
                        submitted_total=(
                            self.metrics.submitted_by_head[name]
                            if deferred is not None else None),
                        now=now)

    # -- failure injection / role scaling ------------------------------------

    def kill_decode_worker(self, worker_id: str) -> int:
        """SIGKILL-style decode-worker death: its resident KV is gone,
        every flight it held is re-submitted typed + at-most-once back
        through the prefill path on the survivors. Returns the stranded
        count."""
        with self._runtime:
            group, worker = self._find(worker_id, "decode")
            group.decode.remove(worker)
            stranded = worker.kill()
            group.transport.forget(worker.pool)
            self._counters["decode_worker_deaths"] += 1
            self._flight.record(
                "disagg_worker_dead", worker=worker_id, role="decode",
                head=group.head.name, stranded=len(stranded),
                survivors=len(group.decode),
            )
            self._log.warning(
                f"disagg: decode worker {worker_id} died with "
                f"{len(stranded)} requests resident — re-submitting "
                f"through {len(group.decode)} survivors"
            )
            for fl in stranded:
                self._resubmit(group, fl, from_worker=worker_id)
        with self._lock:
            self._work.notify()
        return len(stranded)

    def kill_prefill_worker(self, worker_id: str) -> int:
        """Prefill-worker death: nothing decoded is lost (its queue
        holds un-prefilled requests), but its retained prefix pages and
        queue die with it — queued flights re-route to surviving prefill
        workers (no retry spent: no accepted work was lost)."""
        with self._runtime:
            group, worker = self._find(worker_id, "prefill")
            group.prefill.remove(worker)
            worker.dead = True
            worker.clear_prefix_cache("worker_killed")
            group.transport.forget(worker.pool)
            with self._lock:
                stranded = list(worker.queue)
                worker.queue.clear()
            self._counters["prefill_worker_deaths"] += 1
            self._flight.record(
                "disagg_worker_dead", worker=worker_id, role="prefill",
                head=group.head.name, stranded=len(stranded),
                survivors=len(group.prefill),
            )
            for fl in stranded:
                try:
                    with self._lock:
                        self._enqueue_locked(fl)
                except WorkerLostError as e:
                    if not fl.fut.done():
                        fl.fut.set_exception(e)
                        self.metrics.record_failure(1)
        with self._lock:
            self._work.notify()
        return len(stranded)

    def _resubmit(self, group: _HeadGroup, flight: Flight,
                  from_worker: str) -> None:
        if flight.fut.done():
            return
        if flight.retried:
            flight.fut.set_exception(WorkerLostError(
                f"request lost decode worker {from_worker} after already "
                "being re-submitted once (at-most-once retry exhausted)"
            ))
            self.metrics.record_failure(1)
            return
        live_decode = [w for w in group.decode if not w.dead]
        if not live_decode:
            flight.fut.set_exception(WorkerLostError(
                f"decode worker {from_worker} died and no decode "
                "capacity survives for the re-submit"
            ))
            self.metrics.record_failure(1)
            return
        flight.retried = True
        try:
            with self._lock:
                self._enqueue_locked(flight)
        except WorkerLostError as e:
            flight.fut.set_exception(e)
            self.metrics.record_failure(1)
            return
        self._counters["handoffs_resubmitted"] += 1
        self._flight.record(
            "handoff_resubmitted", head=group.head.name,
            worker_from=from_worker,
            trace_id=flight.trace.trace_id
            if flight.trace is not None else None,
        )

    def _find(self, worker_id: str, role: str):
        for group in self._groups.values():
            pool = group.decode if role == "decode" else group.prefill
            for w in pool:
                if w.worker_id == worker_id:
                    return group, w
        raise KeyError(f"no live {role} worker {worker_id!r}")

    def role_pool(self, head: str, role: str) -> _RolePool:
        if head not in self._heads or role not in ("prefill", "decode"):
            raise KeyError(f"no role pool ({head!r}, {role!r})")
        return _RolePool(self, head, role)

    def _role_signal(self, head: str, role: str) -> dict:
        group = self._groups[head]
        workers = group.prefill if role == "prefill" else group.decode
        per = {}
        with self._lock:
            pending = len(group.pending)
            for w in workers:
                if w.dead or w.draining:
                    continue
                hr = w.headroom()
                if role == "prefill":
                    shedding = len(w.queue) >= 4 * self._max_batch
                else:
                    shedding = w.free_slots == 0 and pending > 0
                per[w.worker_id] = {"headroom": hr, "shedding": shedding}
        return {"replicas": per, "alive": len(per)}

    def _add_worker(self, head: str, role: str) -> str:
        with self._runtime:
            if self._draining:
                raise DrainingError("front is draining; refusing scale-out")
            group = self._groups[head]
            if role == "prefill":
                w = self._make_prefill(group)
                w.warmup()
                group.prefill.append(w)
            elif self._transport_kind == "socket":
                # Scale-out attaches the next standby decode host; the
                # socket tier never builds decode workers in-process.
                if not self._standby_addrs:
                    raise WorkerLostError(
                        "socket-tier decode scale-out needs a standby "
                        "decode host (standby_workers=) — none left"
                    )
                w = self._make_remote_decode(self._standby_addrs.pop(0))
            else:
                w = self._make_decode(group)
                w.warmup()
                group.decode.append(w)
            self._flight.record(
                "disagg_worker_added", worker=w.worker_id, role=role,
                head=head, warmup_compiles=w.warmup_compiles,
            )
        with self._lock:
            self._work.notify()
        return w.worker_id

    def _remove_worker(self, head: str, role: str, worker_id: str,
                       timeout: float) -> dict:
        group, worker = self._find(worker_id, role)
        worker.draining = True
        if role == "prefill":
            # Re-route its queued flights; nothing prefilled is lost.
            # Removing the LAST live prefill worker fails its queue
            # typed — a raise here would strand the flights with their
            # futures never set (callers hang to their own timeouts).
            with self._runtime:
                with self._lock:
                    queued = list(worker.queue)
                    worker.queue.clear()
                group.prefill.remove(worker)
                for fl in queued:
                    try:
                        with self._lock:
                            self._enqueue_locked(fl)
                    except WorkerLostError as e:
                        if not fl.fut.done():
                            fl.fut.set_exception(e)
                            self.metrics.record_failure(1)
                worker.clear_prefix_cache("scale_in")
        else:
            # Graceful: stop routing handoffs to it, let resident slots
            # finish (the loop keeps stepping it), then drop the handle.
            deadline = time.monotonic() + timeout
            while not worker.idle and time.monotonic() < deadline:
                if self._batcher is None:
                    self.pump_once()
                else:
                    time.sleep(0.005)
            if not worker.idle:
                raise TimeoutError(
                    f"decode worker {worker_id} did not drain in "
                    f"{timeout}s"
                )
            with self._runtime:
                group.decode.remove(worker)
                # A removed worker's scratch reservation leaves with it
                # (its refs would pin shared-bank pages forever).
                worker.pool.release_scratch()
                if hasattr(worker, "close"):
                    worker.close()
        group.transport.forget(worker.pool)
        final = worker.stats()
        self._flight.record(
            "disagg_worker_removed", worker=worker_id, role=role,
            head=head,
        )
        return final

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["params_step"] = self._step
        snap["draining"] = self._draining
        workers = [w for g in self._groups.values()
                   for w in g.prefill + g.decode]
        snap["warmup_compiles"] = sum(w.warmup_compiles for w in workers)
        snap["recompilations"] = sum(w.recompilations for w in workers)
        with self._lock:
            depths = {
                name: sum(len(w.queue) for w in g.prefill)
                for name, g in self._groups.items()
            }
        snap["queue_depth"] = depths
        headroom, kv_pool, roles_by_head = {}, {}, {}
        for name, g in self._groups.items():
            pre_live = [w for w in g.prefill if not w.dead and not w.draining]
            dec_live = [w for w in g.decode if not w.dead and not w.draining]
            pre_hr = max((w.headroom() for w in pre_live), default=-1.0)
            dec_hr = max((w.headroom() for w in dec_live), default=-1.0)
            headroom[name] = round(
                min(pre_hr, dec_hr, -1.0 if self._draining else 1.0), 4
            )
            pools = []
            if g.bank is not None:
                pools.append(g.bank)
            else:
                pools.extend(w.pool for w in g.prefill if w.owns_pool)
                pools.extend(w.pool for w in g.decode if w.owns_pool)
            kv_pool[name] = {
                "pages_in_use": sum(p.allocator.pages_in_use for p in pools),
                "pages_free": sum(p.allocator.pages_free for p in pools),
                "slots_active": sum(w.pool.active_slot_count
                                    for w in g.decode),
                "slots_total": sum(w.pool.cfg.max_slots for w in g.decode),
                "kv_tokens_resident": int(sum(
                    w.pool.seq_lens.sum() for w in g.decode
                )),
            }
            roles_by_head[name] = {
                "prefill": {
                    "workers": len(pre_live),
                    "queue_depth": depths[name],
                    "headroom": round(pre_hr, 4),
                    "deferred": sum(w.deferred for w in g.prefill),
                    "per_worker": {w.worker_id: w.stats()
                                   for w in g.prefill},
                },
                "decode": {
                    "workers": len(dec_live),
                    "pending_handoffs": len(g.pending),
                    "slots_active": kv_pool[name]["slots_active"],
                    "slots_total": kv_pool[name]["slots_total"],
                    "headroom": round(dec_hr, 4),
                    "per_worker": {w.worker_id: w.stats()
                                   for w in g.decode},
                },
            }
        snap["headroom"] = headroom
        snap["kv_pool"] = kv_pool
        snap["tracing"] = self._tracer.stats()
        snap["disagg"] = {
            "transport": self._transport_kind,
            **dict(self._counters),
            "pending_handoffs": sum(len(g.pending)
                                    for g in self._groups.values()),
            "degraded_heads": sorted(self._degraded),
            "transfer_ms": self.transfer.summary(),
            "roles": roles_by_head,
        }
        transports = {}
        for g in self._groups.values():
            tstats = g.transport.stats()
            if tstats:
                transports[g.transport.name] = tstats
        if transports:
            snap["disagg"]["transports"] = transports
        if self._slo is not None:
            snap["slo"] = self._slo.snapshot()
        return snap
