"""KV transports: how a `KVHandoff`'s page content crosses worker pools.

Two tiers, one interface, so the cross-host backend later is a transport
swap rather than a redesign:

- `InProcessTransport` — zero-copy. Prefill and decode workers share ONE
  device page bank (`KVPagePool(bank=...)` slot views over the same page
  arrays + refcounted allocator), and the handoff moves a page run by
  REFERENCE: `send` takes a COW ref per page, the receiving pool's
  `admit_shared` binds a slot onto the same pages, and the handoff ref
  drops — exactly the PR-11 prefix-cache machinery generalized across
  pools. ``transfer_bytes`` is 0; the payload never moves.
- `SerializingTransport` — host-roundtrip. The sender gathers the run's
  page content to host (one fixed-shape compiled gather per pool, AOT at
  warmup), packs it through the pinned wire format
  (disagg/handoff.pack_handoff), and the receiver allocates fresh pages
  in its OWN pool and scatters the content in (one fixed-shape compiled
  scatter, AOT at warmup). This pins the wire contract and makes the
  transfer cost a measured quantity (bytes + latency per handoff) —
  the cross-host hop will serialize exactly these bytes.

Both transports keep the compile discipline: every executable is built
at worker warmup (counted there), steady state never compiles.
"""

from __future__ import annotations

import numpy as np

from genrec_tpu.disagg.handoff import (
    HandoffRefusedError,
    KVHandoff,
    pack_handoff,
    unpack_handoff,
)
from genrec_tpu.serving.aot import donate_argnums, sds_tree as _sds_tree
from genrec_tpu.serving.kv_pool import KVPagePool


class KVTransport:
    """Interface both workers program against.

    Lifecycle of one handoff: the prefill worker calls
    ``send(src_pool, pages, handoff)`` (attach the payload — take refs or
    serialize), the front routes it, the decode worker calls
    ``admit(handoff, dst_pool)`` (bind a slot; may raise PoolExhausted —
    the handoff stays pending and retries), and finally
    ``release(handoff)`` drops the in-flight payload refs (after a
    successful admit, a typed refusal, or a kill). All calls run on the
    front's single runtime thread — the same single-writer discipline the
    engine's batcher holds over its pool."""

    name = "abstract"

    def prepare_send(self, pool: KVPagePool, on_compile) -> None:
        """Compile/validate the sender-side path for ``pool`` (worker
        warmup; ``on_compile()`` counts every executable built)."""

    def prepare_admit(self, pool: KVPagePool, on_compile) -> None:
        """Compile/validate the receiver-side path for ``pool``."""

    def send(self, src_pool: KVPagePool, pages, handoff: KVHandoff) -> None:
        raise NotImplementedError

    def admit(self, handoff: KVHandoff, dst_pool: KVPagePool) -> int:
        raise NotImplementedError

    def release(self, handoff: KVHandoff) -> None:
        raise NotImplementedError

    def forget(self, pool: KVPagePool) -> None:
        """Drop any per-pool compiled/cached state — the front calls
        this when a worker is killed or scaled in, so a group-lifetime
        transport does not accumulate executables for dead pools."""

    def stats(self) -> dict:
        """Per-transport wire section for the front's `stats()` snapshot
        (frames/bytes counters + serialize-vs-network latency splits).
        The in-process tier moves references, so it has nothing to
        report."""
        return {}


class InProcessTransport(KVTransport):
    """Zero-copy: every worker pool must be a slot view over ``bank``."""

    name = "inprocess"

    def __init__(self, bank: KVPagePool):
        self.bank = bank

    def _check_pool(self, pool: KVPagePool) -> None:
        if pool.allocator is not self.bank.allocator:
            raise ValueError(
                "in-process transport requires every worker pool to share "
                "the page bank (KVPagePool(bank=...)); this pool has its "
                "own allocator — use the serializing transport instead"
            )

    def prepare_send(self, pool, on_compile) -> None:
        self._check_pool(pool)

    def prepare_admit(self, pool, on_compile) -> None:
        self._check_pool(pool)

    def send(self, src_pool, pages, handoff) -> None:
        # The handoff's own COW ref per page: the run survives the
        # sender's temp ref / prefix entry being dropped, and dies with
        # release() if the handoff never lands.
        src_pool.allocator.addref(pages)
        handoff.pages = list(pages)

    def admit(self, handoff, dst_pool) -> int:
        return dst_pool.admit_shared(handoff.pages, handoff.n_tokens)

    def release(self, handoff) -> None:
        if handoff.pages is not None:
            self.bank.allocator.free(handoff.pages)
            handoff.pages = None


class SerializingTransport(KVTransport):
    """Host-roundtrip: gather -> pinned wire bytes -> scatter."""

    name = "serializing"

    def __init__(self):
        # Compiled per pool object (pools differ in num_pages across
        # roles/workers); built at worker warmup, looked up steady-state.
        # _pools pins a strong ref per cached pool: the id() keys stay
        # valid (a GC'd pool's id can be recycled by a NEW pool, whose
        # prepare_* would then silently reuse the dead pool's
        # executable); `forget` drops all three entries on worker
        # removal.
        self._gather: dict[int, object] = {}
        self._scatter: dict[int, object] = {}
        self._pools: dict[int, KVPagePool] = {}
        # Wire observability (obs/export.py types these by leaf name:
        # frames_*/wire_bytes are counters, the _ms summaries gauges).
        from genrec_tpu.serving.metrics import LatencyHistogram

        self.counters = {
            "frames_sent": 0, "frames_admitted": 0,
            "frames_refused": 0, "wire_bytes": 0,
        }
        self.serialize_ms = LatencyHistogram()

    def stats(self) -> dict:
        return {**self.counters, "serialize_ms": self.serialize_ms.summary()}

    @staticmethod
    def _stage_vec(pool, vec):
        """The page-index vector for a gather/scatter call. A mesh-placed
        pool (worker ``mesh=`` knob) lowered its executables against
        NamedSharding operands — hand those a HOST array and let the
        executable place it; a device-0-committed jnp array would be a
        sharding mismatch. Single-device pools keep the jnp fast path."""
        from jax.sharding import NamedSharding

        leaf = pool.k_pools[0]
        leaf = getattr(leaf, "data", leaf)  # int8 QuantizedKVPool
        if isinstance(getattr(leaf, "sharding", None), NamedSharding):
            return vec
        import jax.numpy as jnp

        return jnp.asarray(vec)

    def forget(self, pool) -> None:
        key = id(pool)
        self._gather.pop(key, None)
        self._scatter.pop(key, None)
        self._pools.pop(key, None)

    def prepare_send(self, pool, on_compile) -> None:
        import jax

        if id(pool) in self._gather:
            return
        P = pool.cfg.pages_per_slot
        quantized = pool.cfg.kv_dtype == "int8"

        def gather(k_pools, v_pools, pages_vec):
            # Quantized pools gather (data, scale) row pairs — the wire
            # moves the int8 content WITH its per-page-row scales, never
            # a dequantized copy.
            if quantized:
                return (tuple(k.take_rows(pages_vec) for k in k_pools),
                        tuple(v.take_rows(pages_vec) for v in v_pools))
            return (tuple(k[pages_vec] for k in k_pools),
                    tuple(v[pages_vec] for v in v_pools))

        args = (
            _sds_tree(pool.k_pools), _sds_tree(pool.v_pools),
            jax.ShapeDtypeStruct((P,), np.int32),
        )
        self._gather[id(pool)] = jax.jit(gather).lower(*args).compile()
        self._pools[id(pool)] = pool
        on_compile(self._gather[id(pool)])

    def prepare_admit(self, pool, on_compile) -> None:
        import jax

        if id(pool) in self._scatter:
            return
        P = pool.cfg.pages_per_slot
        quantized = pool.cfg.kv_dtype == "int8"

        def scatter(k_pools, v_pools, pages_vec, k_content, v_content):
            # Padding rows target the reserved null page 0 — attention
            # never reads it unmasked (ops/paged.py contract), so
            # duplicate index-0 writes are harmless.
            if quantized:
                k_pools = tuple(k.put_rows(pages_vec, c[0], c[1])
                                for k, c in zip(k_pools, k_content))
                v_pools = tuple(v.put_rows(pages_vec, c[0], c[1])
                                for v, c in zip(v_pools, v_content))
                return k_pools, v_pools
            k_pools = tuple(k.at[pages_vec].set(c)
                            for k, c in zip(k_pools, k_content))
            v_pools = tuple(v.at[pages_vec].set(c)
                            for v, c in zip(v_pools, v_content))
            return k_pools, v_pools

        if quantized:
            geo = tuple(pool.k_pools[0].data.shape[1:])
            page_shape = (
                jax.ShapeDtypeStruct((P,) + geo, np.int8),
                jax.ShapeDtypeStruct((P, pool.cfg.page_size), np.float32),
            )
        else:
            page_shape = jax.ShapeDtypeStruct(
                (P,) + tuple(np.shape(pool.k_pools[0])[1:]),
                np.result_type(pool.k_pools[0]),
            )
        args = (
            _sds_tree(pool.k_pools), _sds_tree(pool.v_pools),
            jax.ShapeDtypeStruct((P,), np.int32),
            tuple(page_shape for _ in pool.k_pools),
            tuple(page_shape for _ in pool.v_pools),
        )
        self._scatter[id(pool)] = jax.jit(
            scatter, donate_argnums=donate_argnums(0, 1)
        ).lower(*args).compile()
        self._pools[id(pool)] = pool
        on_compile(self._scatter[id(pool)])

    def send(self, src_pool, pages, handoff) -> None:
        import time

        t0 = time.monotonic()
        gather = self._gather[id(src_pool)]
        P = src_pool.cfg.pages_per_slot
        vec = np.zeros(P, np.int32)
        vec[: len(pages)] = pages
        k_content, v_content = gather(
            src_pool.k_pools, src_pool.v_pools, self._stage_vec(src_pool, vec)
        )
        n = len(pages)
        if src_pool.cfg.kv_dtype == "int8":
            k_host = tuple((np.asarray(d)[:n], np.asarray(s)[:n])
                           for d, s in k_content)
            v_host = tuple((np.asarray(d)[:n], np.asarray(s)[:n])
                           for d, s in v_content)
        else:
            k_host = tuple(np.asarray(k)[:n] for k in k_content)
            v_host = tuple(np.asarray(v)[:n] for v in v_content)
        handoff.wire = pack_handoff(handoff, k_host, v_host)
        handoff.pages = None  # nothing pinned on the sender side
        self.counters["frames_sent"] += 1
        self.counters["wire_bytes"] += len(handoff.wire)
        self.serialize_ms.record(time.monotonic() - t0)

    def admit(self, handoff, dst_pool) -> int:
        try:
            return self._admit(handoff, dst_pool)
        except HandoffRefusedError:
            self.counters["frames_refused"] += 1
            raise

    def _admit(self, handoff, dst_pool) -> int:
        import time

        t0 = time.monotonic()
        parsed = getattr(handoff, "_parsed", None)
        if parsed is None:
            decoded, k_content, v_content = unpack_handoff(handoff.wire)
            # The wire is self-describing; cross-check the framing fields
            # against the routed handoff so a swapped payload cannot ride
            # valid routing metadata.
            if (decoded.head, decoded.n_tokens) != (
                handoff.head, handoff.n_tokens
            ):
                raise HandoffRefusedError(
                    "handoff wire payload disagrees with its routing "
                    f"metadata: {decoded.head}/{decoded.n_tokens} vs "
                    f"{handoff.head}/{handoff.n_tokens}"
                )
            parsed = handoff._parsed = (k_content, v_content)
        k_content, v_content = parsed
        quantized = handoff.kv_dtype == "int8"
        if handoff.kv_dtype != dst_pool.cfg.kv_dtype:
            # Backstop behind DecodeWorker.validate: bytes scattered
            # under the wrong storage dtype would be silent garbage.
            raise HandoffRefusedError(
                f"handoff kv_dtype {handoff.kv_dtype!r} != receiving pool "
                f"kv_dtype {dst_pool.cfg.kv_dtype!r}"
            )
        first = k_content[0][0] if quantized else k_content[0]
        n = first.shape[0]
        if first.shape[1] != dst_pool.cfg.page_size:
            raise HandoffRefusedError(
                f"handoff page size {first.shape[1]} != receiving "
                f"pool page size {dst_pool.cfg.page_size}"
            )
        if n > dst_pool.cfg.pages_per_slot:
            raise HandoffRefusedError(
                f"handoff spans {n} pages but the receiving pool binds "
                f"at most {dst_pool.cfg.pages_per_slot} per slot"
            )
        pages = dst_pool.allocator.alloc(n)  # may raise PoolExhausted
        try:
            P = dst_pool.cfg.pages_per_slot
            vec = np.zeros(P, np.int32)
            vec[:n] = pages

            def _padded(content):
                if n == P:
                    # The run already fills the compiled (P,) rung — the
                    # scatter's only shape. Re-padding here was a full
                    # host copy of every page row per handoff on the max
                    # rung (the common case under long-history load);
                    # skipping it changes no executable (pinned by the
                    # full-rung recompilation check in
                    # tests/test_crosshost.py).
                    return content
                if quantized:
                    pad_d = ((0, P - n),) + ((0, 0),) * (content[0][0].ndim - 1)
                    pad_s = ((0, P - n), (0, 0))
                    return tuple((np.pad(d, pad_d), np.pad(s, pad_s))
                                 for d, s in content)
                pad = ((0, P - n),) + ((0, 0),) * (content[0].ndim - 1)
                return tuple(np.pad(c, pad) for c in content)

            scatter = self._scatter[id(dst_pool)]
            k_pools, v_pools = scatter(
                dst_pool.k_pools, dst_pool.v_pools,
                self._stage_vec(dst_pool, vec),
                _padded(k_content), _padded(v_content),
            )
            dst_pool.k_pools, dst_pool.v_pools = k_pools, v_pools
            slot = dst_pool.bind_pages(pages, handoff.n_tokens)
            self.counters["frames_admitted"] += 1
            self.serialize_ms.record(time.monotonic() - t0)
            return slot
        except Exception:
            dst_pool.allocator.free(pages)
            raise

    def release(self, handoff) -> None:
        handoff.wire = None
        if hasattr(handoff, "_parsed"):
            handoff._parsed = None
