"""Fused residual-quantization cascade Pallas kernel.

Semantic-id extraction is a hot loop (SURVEY.md §3.1: collision-rate eval
re-encodes EVERY item each eval; datasets tokenize the full catalog). The
XLA path runs L sequential Quantize layers, each materializing a (B, K)
distance matrix and the intermediate residual in HBM. This kernel keeps
one batch tile resident in VMEM for the whole cascade:

    per level l:  dist = |c|^2 - 2 x_res @ C_l^T      (MXU)
                  ids  = argmin(dist)
                  x_res -= onehot(ids) @ C_l           (MXU gather)

The codeword gather is a one-hot matmul — TPU-friendly, no dynamic row
gather. Applies to the raw-codebook configuration (no sim_vq projection /
normalization — the shipped RQ-VAE configs); the general path falls back
to the Flax model.

MEASURED VERDICT (v5e, results/tpu/bench.json kernel_preflight): at
rqvae scale (B=2048, D=32, L=3, K=256) the op is too small for a custom
kernel to pay off — XLA 0.17 ms vs Pallas 1.50 ms; per-tile grid
overhead dominates an op whose whole working set is ~0.3 MB. The kernel
stays correct (ids match bitwise, preflight-gated) but OFF by default
(`rqvae_trainer use_pallas=False`); the framework's winning kernels are
the fused HSTU attention (fwd+bwd) and the fused linear+CE
(kernels/fused_ce.py), which attack measured memory-bound costs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, cb_ref, ids_ref, qsum_ref, *, n_layers: int, K: int):
    x = x_ref[...].astype(jnp.float32)  # (blk_b, D)
    res = x
    qsum = jnp.zeros_like(x)
    for l in range(n_layers):
        cb = cb_ref[l].astype(jnp.float32)  # (Kp, D)
        c2 = jnp.sum(cb * cb, axis=1)  # (Kp,)
        # HIGHEST: the MXU's default single-pass bf16 rounds distances
        # enough to flip near-tie argmins, and one flipped id at level 0
        # cascades through every later level (seen on v5e).
        dist = c2[None, :] - 2.0 * jnp.dot(
            res, cb.T, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (blk_b, Kp)
        # Padded codeword columns (>= K) can never win the argmin.
        col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1)
        dist = jnp.where(col >= K, jnp.inf, dist)
        # First-occurrence argmin via min-reductions only: jnp.argmin's
        # lowering hits a Mosaic f32->i32 vector legalization bug at some
        # padded-lane shapes (seen at K=32 -> Kp=128 on v5e).
        row_min = jnp.min(dist, axis=1, keepdims=True)
        ids = jnp.min(jnp.where(dist == row_min, col, dist.shape[1]), axis=1)
        onehot = (col == ids[:, None]).astype(jnp.float32)
        chosen = jnp.dot(
            onehot, cb, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        res = res - chosen
        qsum = qsum + chosen
        ids_ref[l, :] = ids.astype(jnp.int32)
    qsum_ref[...] = qsum.astype(qsum_ref.dtype)


def _round_up(x, m):
    return (x + m - 1) // m * m


def rq_cascade_pallas(
    x, codebooks, blk_b: int = 256, interpret: bool = False
):
    """x: (B, D) residual inputs (already encoded); codebooks: (L, K, D).

    Returns (sem_ids (B, L) int32, quantized_sum (B, D)).
    """
    B, D = x.shape
    L, K, _ = codebooks.shape
    interpret = interpret or jax.default_backend() != "tpu"
    Bp = _round_up(B, blk_b)
    Dp = _round_up(D, 128)
    Kp = _round_up(K, 128)

    xf = jnp.pad(x, ((0, Bp - B), (0, Dp - D)))
    # Padded codeword rows are excluded inside the kernel (iota mask on
    # columns >= K), so zero-padding is safe here.
    cbf = jnp.pad(codebooks, ((0, 0), (0, Kp - K), (0, Dp - D)))

    kernel = functools.partial(_kernel, n_layers=L, K=K)
    # ids come out as (L, B): with B on the lane dim the int32 output tiles
    # cleanly, whereas (B, L) pads the L=3 lane to 128 and (together with
    # 3-D blocked outputs) blew the 16MB scoped-vmem stack limit on v5e —
    # the round-1 compiled-path failure.
    ids, qsum = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((L, Bp), jnp.int32),
            jax.ShapeDtypeStruct((Bp, Dp), x.dtype),
        ),
        grid=(Bp // blk_b,),
        in_specs=[
            pl.BlockSpec((blk_b, Dp), lambda i: (i, 0)),
            pl.BlockSpec((L, Kp, Dp), lambda i: (0, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((L, blk_b), lambda i: (0, i)),
            pl.BlockSpec((blk_b, Dp), lambda i: (i, 0)),
        ),
        # The unrolled cascade keeps ~(5 + 4*L) live (blk_b, Kp) fp32
        # temporaries; Mosaic's conservative liveness puts that at ~32MB
        # for blk_b=256/L=3 — over the 16MB default scoped-vmem stack.
        # v5e has 128MB VMEM; 64MB headroom measured OK on hardware.
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=64 * 2**20),
        interpret=interpret,
    )(xf, cbf)
    return (
        ids.T[:B],
        qsum[:B, :D],
    )
