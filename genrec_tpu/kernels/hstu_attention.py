"""Fused HSTU SiLU-attention Pallas kernel.

The reference materializes a (B, H, L, L) float bias tensor per layer per
step (hstu.py:386-409) — at L=50 that's noise, but it scales O(L^2) in HBM
traffic and is exactly what SURVEY.md §5.7 flags as the kernel-fusion
target. This kernel computes, per (batch*head, q-block) tile:

    scores = Q_blk @ K^T                       (MXU, fp32 accumulate)
    scores += pos_bias[bucket(j - i)]          (bucket math in-registers)
    scores += time_bias[bucket(|t_i - t_j|)]
    scores  = -1e9 where causal/padding masked
    out     = silu(scores) @ V                 (MXU)

so neither bias nor the (L, L) score matrix ever round-trips to HBM.
Bias-table lookups use a one-hot select loop over the (tiny) bucket tables
— TPU-friendly, no dynamic gather.

`hstu_attention` wraps the kernel in jax.custom_vjp with a fused Pallas
backward (`hstu_attention_bwd_pallas`): each (batch*head, q-block) tile
recomputes scores + biases flash-style (nothing saved but the inputs),
then emits dq per tile, accumulates dk/dv into revisited output blocks
across the sequentially-executed q-block grid dimension, and writes
per-tile bias-table partials that XLA sums afterwards — so training,
like inference, never materializes the (B, H, L, L) score/bias tensors
the reference does (hstu.py:386-409).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e9


def _pos_bucket_f(rel, num_buckets, max_distance):
    """hstu_position_bucket (ops/buckets.py) in kernel-safe form."""
    rp = jnp.maximum(rel, 0)
    max_exact = num_buckets // 2
    large = max_exact + (
        jnp.log(jnp.maximum(rp, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return jnp.where(rp < max_exact, rp, large)


def _time_bucket_f(diff, num_buckets):
    abs_diff = jnp.maximum(jnp.abs(diff), 1).astype(jnp.float32)
    b = (jnp.log(abs_diff) / 0.693).astype(jnp.int32)
    return jnp.clip(b, 0, num_buckets - 1)


def _kernel(
    q_ref, k_ref, v_ref, ts_ref, tsq_ref, mask_ref, seg_ref, segq_ref,
    ptab_ref, ttab_ref, out_ref,
    *, blk_q: int, num_pos_buckets: int, num_time_buckets: int,
    max_position_distance: int, use_time: bool, use_seg: bool,
):
    j = pl.program_id(1)
    L = k_ref.shape[1]

    q = q_ref[0]  # (blk_q, hd)
    k = k_ref[0]  # (L, hd)
    scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (blk_q, L)

    q_pos = j * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, L), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (blk_q, L), 1)

    # Replicated reference quirk: rel = key - query, clamped >= 0 in the
    # bucket fn (see models/hstu.py RelativePositionBias).
    pbucket = _pos_bucket_f(k_pos - q_pos, num_pos_buckets, max_position_distance)
    pbias = jnp.zeros_like(scores)
    for b in range(num_pos_buckets):
        pbias = pbias + jnp.where(pbucket == b, ptab_ref[0, 0, b], 0.0)
    scores = scores + pbias

    if use_time:
        ts = ts_ref[0]  # (1, L) int32
        # The q-tile timestamps arrive as their own blocked operand —
        # dynamic_slice on a ref is not lowerable in Mosaic TC kernels.
        t_q = tsq_ref[0]  # (1, blk_q)
        tdiff = t_q.T - ts[0][None, :]  # (blk_q, L)
        tbucket = _time_bucket_f(tdiff, num_time_buckets)
        tbias = jnp.zeros_like(scores)
        for b in range(num_time_buckets):
            tbias = tbias + jnp.where(tbucket == b, ttab_ref[0, 0, b], 0.0)
        scores = scores + tbias

    causal_or_pad = jnp.logical_or(k_pos > q_pos, mask_ref[0, 0][None, :] != 0)
    if use_seg:
        # Packed rows: a query must not see keys from another segment
        # (same in-register fold as the causal/padding mask — packing does
        # not force the unfused fallback).
        seg_k = seg_ref[0, 0][None, :]  # (1, L)
        seg_q = segq_ref[0, 0][:, None]  # (blk_q, 1)
        causal_or_pad = jnp.logical_or(causal_or_pad, seg_q != seg_k)
    scores = jnp.where(causal_or_pad, NEG, scores)
    attn = scores * jax.nn.sigmoid(scores)  # silu
    out_ref[0] = jnp.dot(
        attn.astype(v_ref.dtype), v_ref[0], preferred_element_type=jnp.float32
    ).astype(out_ref.dtype)


def _round_up(x, m):
    return (x + m - 1) // m * m


def _pad(x, target_len, axis, value=0):
    cfg = [(0, 0)] * x.ndim
    cfg[axis] = (0, target_len - x.shape[axis])
    return jnp.pad(x, cfg, constant_values=value)


def _pad_inputs(q, k, v, timestamps, padding_mask, time_table, blk_q,
                segment_ids=None):
    """Shared fwd/bwd input prep: flatten (B,H) and pad L to the q-block
    multiple and hd to the 128-lane multiple. Padded key positions are
    masked (value=1); absent timestamps/time_table/segment_ids get inert
    zeros so the operand list keeps a static shape. The forward and
    backward kernels recompute identical scores only because they run
    through this ONE helper."""
    B, H, L, hd = q.shape
    Lp = _round_up(L, blk_q)
    hp = _round_up(hd, 128)
    qf = _pad(_pad(q.reshape(B * H, L, hd), Lp, 1), hp, 2)
    kf = _pad(_pad(k.reshape(B * H, L, hd), Lp, 1), hp, 2)
    vf = _pad(_pad(v.reshape(B * H, L, hd), Lp, 1), hp, 2)
    maskf = _pad(padding_mask.astype(jnp.int32), Lp, 1, value=1)
    if timestamps is not None and time_table is not None:
        tsf = _pad(timestamps.astype(jnp.int32), Lp, 1)
    else:
        tsf = jnp.zeros((B, Lp), jnp.int32)
        time_table = jnp.zeros((H, 1), jnp.float32)
    if segment_ids is not None:
        segf = _pad(segment_ids.astype(jnp.int32), Lp, 1)
    else:
        segf = jnp.zeros((B, Lp), jnp.int32)
    return qf, kf, vf, maskf, tsf, segf, time_table, Lp, hp


def hstu_attention_pallas(
    q, k, v, timestamps, padding_mask, pos_table, time_table,
    max_position_distance: int = 128, blk_q: int = 128, interpret: bool = False,
    segment_ids=None,
):
    """Fused SiLU attention.

    Args:
        q, k, v: (B, H, L, hd)
        timestamps: (B, L) int32 or None
        padding_mask: (B, L) bool/int — True/1 = padding
        pos_table: (H, num_pos_buckets)
        time_table: (H, num_time_buckets) or None
        segment_ids: (B, L) int32 or None — packed-row segments (0 = pad);
            cross-segment pairs are masked in-register.
    Returns:
        (B, H, L, hd) attention output (same dtype as v).
    """
    B, H, L, hd = q.shape
    use_time = timestamps is not None and time_table is not None
    use_seg = segment_ids is not None
    # Mosaic compiles only on TPU; elsewhere fall back to the interpreter
    # so use_pallas=True models stay runnable (slowly) in CI.
    interpret = interpret or jax.default_backend() != "tpu"
    qf, kf, vf, maskf, tsf, segf, time_table, Lp, hp = _pad_inputs(
        q, k, v, timestamps, padding_mask, time_table, blk_q, segment_ids
    )
    n_q = Lp // blk_q
    grid = (B * H, n_q)

    kernel = functools.partial(
        _kernel,
        blk_q=blk_q,
        num_pos_buckets=pos_table.shape[1],
        num_time_buckets=time_table.shape[1],
        max_position_distance=max_position_distance,
        use_time=use_time,
        use_seg=use_seg,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, Lp, hp), v.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, hp), lambda i, j: (i, j, 0)),  # q block
            pl.BlockSpec((1, Lp, hp), lambda i, j: (i, 0, 0)),  # full k
            pl.BlockSpec((1, Lp, hp), lambda i, j: (i, 0, 0)),  # full v
            # Small per-batch/per-head operands carry a leading select dim:
            # Mosaic requires the last two BLOCK dims to be (8,128)-aligned
            # or equal to the full array dims, and leading dims are free —
            # a (1, Lp) block over (B, Lp) is illegal when B != 1 (the
            # round-1 compiled-path failure).
            pl.BlockSpec((1, 1, Lp), lambda i, j: (i // H, 0, 0)),  # timestamps (keys)
            pl.BlockSpec((1, 1, blk_q), lambda i, j: (i // H, 0, j)),  # ts q-tile
            pl.BlockSpec((1, 1, Lp), lambda i, j: (i // H, 0, 0)),  # padding mask
            pl.BlockSpec((1, 1, Lp), lambda i, j: (i // H, 0, 0)),  # segments (keys)
            pl.BlockSpec((1, 1, blk_q), lambda i, j: (i // H, 0, j)),  # seg q-tile
            pl.BlockSpec((1, 1, pos_table.shape[1]), lambda i, j: (i % H, 0, 0)),
            pl.BlockSpec((1, 1, time_table.shape[1]), lambda i, j: (i % H, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, hp), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf, tsf[:, None], tsf[:, None], maskf[:, None],
      segf[:, None], segf[:, None], pos_table[:, None], time_table[:, None])
    return out.reshape(B, H, Lp, hp)[:, :, :L, :hd]


def _bwd_kernel(
    q_ref, k_ref, v_ref, do_ref, ts_ref, tsq_ref, mask_ref, seg_ref, segq_ref,
    ptab_ref, ttab_ref,
    dq_ref, dk_ref, dv_ref, dpt_ref, dtt_ref,
    *, blk_q: int, num_pos_buckets: int, num_time_buckets: int,
    max_position_distance: int, use_time: bool, use_seg: bool,
):
    j = pl.program_id(1)
    L = k_ref.shape[1]

    q = q_ref[0].astype(jnp.float32)  # (blk_q, hd)
    k = k_ref[0].astype(jnp.float32)  # (L, hd)
    v = v_ref[0].astype(jnp.float32)  # (L, hd)
    do = do_ref[0].astype(jnp.float32)  # (blk_q, hd)

    # --- Recompute the masked scores exactly as the forward kernel does.
    scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (blk_q, L)
    q_pos = j * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, L), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (blk_q, L), 1)
    pbucket = _pos_bucket_f(k_pos - q_pos, num_pos_buckets, max_position_distance)
    pbias = jnp.zeros_like(scores)
    for b in range(num_pos_buckets):
        pbias = pbias + jnp.where(pbucket == b, ptab_ref[0, 0, b], 0.0)
    scores = scores + pbias
    if use_time:
        ts = ts_ref[0]
        t_q = tsq_ref[0]
        tdiff = t_q.T - ts[0][None, :]
        tbucket = _time_bucket_f(tdiff, num_time_buckets)
        tbias = jnp.zeros_like(scores)
        for b in range(num_time_buckets):
            tbias = tbias + jnp.where(tbucket == b, ttab_ref[0, 0, b], 0.0)
        scores = scores + tbias

    masked = jnp.logical_or(k_pos > q_pos, mask_ref[0, 0][None, :] != 0)
    if use_seg:
        masked = jnp.logical_or(
            masked, segq_ref[0, 0][:, None] != seg_ref[0, 0][None, :]
        )
    s = jnp.where(masked, NEG, scores)

    # --- Local grads. silu(s) = s*sig(s); silu'(s) = sig(s)*(1 + s*(1-sig(s))).
    sig = jax.nn.sigmoid(s)
    attn = s * sig  # (blk_q, L)
    d_attn = jnp.dot(do, v.T, preferred_element_type=jnp.float32)  # (blk_q, L)
    # Gradient at the PRE-mask scores: masked entries get exactly zero
    # (the where() in the forward routes no gradient to them).
    ds = jnp.where(masked, 0.0, d_attn * sig * (1.0 + s * (1.0 - sig)))

    # --- Input grads. dq per tile; dk/dv accumulate across the j grid
    # dim (sequential on TPU; the output blocks are revisited).
    dq_ref[0] = jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    dk_ref[0] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
    dv_ref[0] += jnp.dot(attn.T, do, preferred_element_type=jnp.float32)

    # --- Bias-table partials for this tile (summed over tiles in XLA).
    @pl.when(j == 0)
    def _init_tabs():
        dpt_ref[0] = jnp.zeros_like(dpt_ref[0])
        # Zero even when use_time is False (1-wide dummy table): the output
        # buffer is otherwise uninitialized memory for any future consumer.
        dtt_ref[0] = jnp.zeros_like(dtt_ref[0])

    dpt = [jnp.sum(jnp.where(pbucket == b, ds, 0.0)) for b in range(num_pos_buckets)]
    dpt_ref[0] += jnp.stack(dpt)[None, :]
    if use_time:
        dtt = [
            jnp.sum(jnp.where(tbucket == b, ds, 0.0))
            for b in range(num_time_buckets)
        ]
        dtt_ref[0] += jnp.stack(dtt)[None, :]


def hstu_attention_bwd_pallas(
    q, k, v, timestamps, padding_mask, pos_table, time_table, g,
    max_position_distance: int = 128, blk_q: int = 128, interpret: bool = False,
    segment_ids=None,
):
    """Fused flash-style backward. Returns (dq, dk, dv, dpos_table,
    dtime_table) with input dtypes; accumulation is fp32 in-kernel."""
    B, H, L, hd = q.shape
    use_time = timestamps is not None and time_table is not None
    use_seg = segment_ids is not None
    interpret = interpret or jax.default_backend() != "tpu"
    qf, kf, vf, maskf, tsf, segf, ttab, Lp, hp = _pad_inputs(
        q, k, v, timestamps, padding_mask, time_table, blk_q, segment_ids
    )
    gf = _pad(_pad(g.reshape(B * H, L, hd), Lp, 1), hp, 2)
    n_q = Lp // blk_q
    grid = (B * H, n_q)
    nb, ntb = pos_table.shape[1], ttab.shape[1]

    kernel = functools.partial(
        _bwd_kernel,
        blk_q=blk_q,
        num_pos_buckets=nb,
        num_time_buckets=ntb,
        max_position_distance=max_position_distance,
        use_time=use_time,
        use_seg=use_seg,
    )
    dq, dk, dv, dpt, dtt = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lp, hp), jnp.float32),  # dq
            jax.ShapeDtypeStruct((B * H, Lp, hp), jnp.float32),  # dk
            jax.ShapeDtypeStruct((B * H, Lp, hp), jnp.float32),  # dv
            jax.ShapeDtypeStruct((B * H, 1, nb), jnp.float32),  # dpos partials
            jax.ShapeDtypeStruct((B * H, 1, ntb), jnp.float32),  # dtime partials
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, hp), lambda i, j: (i, j, 0)),  # q block
            pl.BlockSpec((1, Lp, hp), lambda i, j: (i, 0, 0)),  # full k
            pl.BlockSpec((1, Lp, hp), lambda i, j: (i, 0, 0)),  # full v
            pl.BlockSpec((1, blk_q, hp), lambda i, j: (i, j, 0)),  # dO block
            pl.BlockSpec((1, 1, Lp), lambda i, j: (i // H, 0, 0)),  # ts (keys)
            pl.BlockSpec((1, 1, blk_q), lambda i, j: (i // H, 0, j)),  # ts q-tile
            pl.BlockSpec((1, 1, Lp), lambda i, j: (i // H, 0, 0)),  # padding mask
            pl.BlockSpec((1, 1, Lp), lambda i, j: (i // H, 0, 0)),  # segments (keys)
            pl.BlockSpec((1, 1, blk_q), lambda i, j: (i // H, 0, j)),  # seg q-tile
            pl.BlockSpec((1, 1, nb), lambda i, j: (i % H, 0, 0)),
            pl.BlockSpec((1, 1, ntb), lambda i, j: (i % H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, hp), lambda i, j: (i, j, 0)),  # dq per tile
            pl.BlockSpec((1, Lp, hp), lambda i, j: (i, 0, 0)),  # dk accumulated
            pl.BlockSpec((1, Lp, hp), lambda i, j: (i, 0, 0)),  # dv accumulated
            pl.BlockSpec((1, 1, nb), lambda i, j: (i, 0, 0)),  # dpos accumulated
            pl.BlockSpec((1, 1, ntb), lambda i, j: (i, 0, 0)),  # dtime accumulated
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, tsf[:, None], tsf[:, None], maskf[:, None],
      segf[:, None], segf[:, None], pos_table[:, None], ttab[:, None])

    dq = dq.reshape(B, H, Lp, hp)[:, :, :L, :hd].astype(q.dtype)
    dk = dk.reshape(B, H, Lp, hp)[:, :, :L, :hd].astype(k.dtype)
    dv = dv.reshape(B, H, Lp, hp)[:, :, :L, :hd].astype(v.dtype)
    # Per-(b,h) partials -> per-head tables (sum over the batch).
    dpt = dpt.reshape(B, H, nb).sum(0).astype(pos_table.dtype)
    dttab = (
        dtt.reshape(B, H, ntb).sum(0).astype(time_table.dtype) if use_time else None
    )
    return dq, dk, dv, dpt, dttab


def hstu_attention_xla(
    q, k, v, timestamps, padding_mask, pos_table, time_table,
    max_position_distance: int = 128, segment_ids=None,
):
    """Reference-shaped XLA implementation (materializes the bias); used as
    fallback and as the source of the backward pass."""
    from genrec_tpu.ops.buckets import hstu_log_bucket, hstu_position_bucket

    B, H, L, hd = q.shape
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    pos = jnp.arange(L)
    rel = pos[None, :] - pos[:, None]  # [i, j] = j - i (reference quirk)
    pbuckets = hstu_position_bucket(rel, pos_table.shape[1], max_position_distance)
    scores = scores + pos_table.T[pbuckets].transpose(2, 0, 1)[None]
    if timestamps is not None and time_table is not None:
        diff = timestamps[:, :, None] - timestamps[:, None, :]
        tbuckets = hstu_log_bucket(diff, time_table.shape[1])
        scores = scores + time_table.T[tbuckets].transpose(0, 3, 1, 2)
    causal = jnp.triu(jnp.ones((L, L), bool), k=1)
    scores = jnp.where(causal[None, None], NEG, scores)
    scores = jnp.where(padding_mask.astype(bool)[:, None, None, :], NEG, scores)
    if segment_ids is not None:
        cross = segment_ids[:, :, None] != segment_ids[:, None, :]  # (B, L, L)
        scores = jnp.where(cross[:, None], NEG, scores)
    attn = jax.nn.silu(scores).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", attn, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def hstu_attention(q, k, v, timestamps, padding_mask, pos_table, time_table,
                   segment_ids=None, max_position_distance=128):
    """Kernel forward + fused flash-style Pallas backward."""
    return hstu_attention_pallas(
        q, k, v, timestamps, padding_mask, pos_table, time_table,
        max_position_distance, segment_ids=segment_ids,
    )


def _fwd(q, k, v, timestamps, padding_mask, pos_table, time_table, segment_ids,
         mpd):
    out = hstu_attention_pallas(
        q, k, v, timestamps, padding_mask, pos_table, time_table, mpd,
        segment_ids=segment_ids,
    )
    return out, (q, k, v, timestamps, padding_mask, pos_table, time_table,
                 segment_ids)


def _bwd(mpd, res, g):
    q, k, v, timestamps, padding_mask, pos_table, time_table, segment_ids = res
    dq, dk, dv, dpt, dtt = hstu_attention_bwd_pallas(
        q, k, v, timestamps, padding_mask, pos_table, time_table, g, mpd,
        segment_ids=segment_ids,
    )
    if dtt is None and time_table is not None:
        dtt = jnp.zeros_like(time_table)
    return dq, dk, dv, None, None, dpt, dtt, None


hstu_attention.defvjp(_fwd, _bwd)
