"""Pallas TPU kernels for the hot ops.

The reference has no native kernels at all (SURVEY.md §2: 100% Python,
stock torch ops); this package is where the new framework's "native layer"
lives: fused HSTU attention (rel/temporal bias computed inside the tile),
with the XLA implementations as both fallback and backward-pass source.
"""
