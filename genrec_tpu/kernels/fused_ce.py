"""Fused full-softmax cross-entropy over a linear head (Pallas).

The reference SASRec/HSTU heads materialize (B, L, V) logits in HBM
(`logits = x @ emb.T` then CE, sasrec.py:121-128) — at Amazon scale
(B·L=6400 rows, V~12-22k items) that is hundreds of MB of HBM traffic per
step for a tensor that is immediately reduced to one scalar per row. This
kernel computes the EXACT same loss (full softmax, ignore_index
semantics) without ever writing the logits:

  forward:  grid (row-block, vocab-block), vocab innermost. Each tile
            computes its (blk_r, blk_v) logits on the MXU and folds them
            into running (max, sumexp, target-logit) accumulators held in
            VMEM scratch (online logsumexp, the flash-attention trick).
            The last vocab step writes per-row loss and logsumexp.
  backward: two kernels recompute tile logits flash-style:
            dx accumulates g*(softmax - onehot) @ W over vocab blocks;
            dW runs the transposed grid and accumulates over row blocks.

Exactness (vs sampled softmax, the other candidate the north star names)
keeps training parity with the reference bit-comparable in expectation —
nothing about the loss changes, only where it is computed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _round_up(x, m):
    return (x + m - 1) // m * m


def _tile_logits(x_ref, w_ref, vlim, j, blk_v, V):
    """(blk_r, blk_v) fp32 logits for this tile; cols at/past min(V, vlim)
    — shape padding or live vocab limit (head pad rows under TP) — at NEG."""
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    col = j * blk_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    limit = jnp.minimum(jnp.int32(V), vlim)
    return jnp.where(col < limit, logits, NEG), col


def _fwd_kernel(x_ref, w_ref, v_ref, tgt_ref, loss_ref, lse_ref, m_sc, s_sc,
                t_sc, *, blk_v: int, V: int, ignore_index: int):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    logits, col = _tile_logits(x_ref, w_ref, v_ref[0, 0], j, blk_v, V)
    tgt = tgt_ref[0, 0]  # (blk_r,)
    # Target logit if it falls inside this vocab tile (sum-select: no
    # dynamic gather on TPU).
    t_here = jnp.sum(jnp.where(col == tgt[:, None], logits, 0.0), axis=1)

    @pl.when(j == 0)
    def _init():
        m_sc[0] = jnp.full_like(m_sc[0], NEG)
        s_sc[0] = jnp.zeros_like(s_sc[0])
        t_sc[0] = jnp.zeros_like(t_sc[0])

    m_old = m_sc[0]
    m_new = jnp.maximum(m_old, jnp.max(logits, axis=1))
    s_sc[0] = s_sc[0] * jnp.exp(m_old - m_new) + jnp.sum(
        jnp.exp(logits - m_new[:, None]), axis=1
    )
    m_sc[0] = m_new
    t_sc[0] = t_sc[0] + t_here

    @pl.when(j == nj - 1)
    def _fin():
        lse = m_sc[0] + jnp.log(s_sc[0])
        loss = lse - t_sc[0]
        loss_ref[0, 0] = jnp.where(tgt == ignore_index, 0.0, loss)
        lse_ref[0, 0] = lse


def _dx_kernel(x_ref, w_ref, v_ref, tgt_ref, lse_ref, g_ref, dx_ref,
               *, blk_v: int, V: int):
    j = pl.program_id(1)
    logits, col = _tile_logits(x_ref, w_ref, v_ref[0, 0], j, blk_v, V)
    p = jnp.exp(logits - lse_ref[0, 0][:, None])  # softmax tile
    onehot = (col == tgt_ref[0, 0][:, None]).astype(jnp.float32)
    coeff = g_ref[0, 0][:, None] * (p - onehot)  # (blk_r, blk_v)

    @pl.when(j == 0)
    def _init():
        dx_ref[...] = jnp.zeros_like(dx_ref[...])

    dx_ref[...] += jnp.dot(
        coeff, w_ref[...].astype(jnp.float32), preferred_element_type=jnp.float32
    )


def _dw_kernel(x_ref, w_ref, v_ref, tgt_ref, lse_ref, g_ref, dw_ref,
               *, blk_v: int, V: int):
    # Transposed grid: i = vocab block, inner j = row block.
    i = pl.program_id(0)
    j = pl.program_id(1)
    logits, col = _tile_logits(x_ref, w_ref, v_ref[0, 0], i, blk_v, V)
    p = jnp.exp(logits - lse_ref[0, 0][:, None])
    onehot = (col == tgt_ref[0, 0][:, None]).astype(jnp.float32)
    coeff = g_ref[0, 0][:, None] * (p - onehot)  # (blk_r, blk_v)

    @pl.when(j == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref[...])

    dw_ref[...] += jax.lax.dot_general(
        coeff, x_ref[...].astype(jnp.float32),
        (((0,), (0,)), ((), ())),  # coeff^T @ x -> (blk_v, dp)
        preferred_element_type=jnp.float32,
    )


def _prep(x, w, targets, blk_r, blk_v):
    R, d = x.shape
    V = w.shape[0]
    Rp, Vp, dp = _round_up(R, blk_r), _round_up(V, blk_v), _round_up(d, 128)
    xf = jnp.pad(x, ((0, Rp - R), (0, dp - d)))
    wf = jnp.pad(w, ((0, Vp - V), (0, dp - d)))
    # Padded rows get target -1: never equal to any column, never ignored
    # into the loss (their loss rows are sliced off anyway).
    tf = jnp.pad(targets.astype(jnp.int32), (0, Rp - R), constant_values=-1)
    tf = tf.reshape(Rp // blk_r, 1, blk_r)
    return xf, wf, tf, R, V, Rp, Vp, dp


def fused_linear_ce_fwd(x, w, targets, ignore_index=0, blk_r=128, blk_v=512,
                        interpret: bool = False, vlim=None):
    """Per-row CE losses (0 at ignored rows) and per-row logsumexp.

    x: (R, d) activations; w: (V, d) head weights (logits = x @ w.T);
    targets: (R,) int. ``vlim`` (optional traced int32): live-vocab limit —
    cols at/past it are excluded from the softmax (head pad rows under TP).
    Returns (loss (R,) f32, lse (R,) f32)."""
    interpret = interpret or jax.default_backend() != "tpu"
    xf, wf, tf, R, V, Rp, Vp, dp = _prep(x, w, targets, blk_r, blk_v)
    n_rb, n_vb = Rp // blk_r, Vp // blk_v
    vf = jnp.full((1, 1), V if vlim is None else vlim, jnp.int32)

    kernel = functools.partial(
        _fwd_kernel, blk_v=blk_v, V=V, ignore_index=ignore_index
    )
    loss, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((n_rb, 1, blk_r), jnp.float32),
            jax.ShapeDtypeStruct((n_rb, 1, blk_r), jnp.float32),
        ],
        grid=(n_rb, n_vb),
        in_specs=[
            pl.BlockSpec((blk_r, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((blk_v, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (i, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, blk_r), jnp.float32),
            pltpu.VMEM((1, blk_r), jnp.float32),
            pltpu.VMEM((1, blk_r), jnp.float32),
        ],
        interpret=interpret,
    )(xf, wf, vf, tf)
    return loss.reshape(Rp)[:R], lse.reshape(Rp)[:R]


def fused_linear_ce_bwd(x, w, targets, lse, g, ignore_index=0, blk_r=128,
                        blk_v=512, interpret: bool = False, vlim=None):
    """(dx, dw) for the fused CE. g: (R,) cotangent of the per-row losses.
    Ignored rows must carry g=0 (the forward zeroed their losses, so any
    upstream reduction gives them zero cotangent through the where)."""
    interpret = interpret or jax.default_backend() != "tpu"
    xf, wf, tf, R, V, Rp, Vp, dp = _prep(x, w, targets, blk_r, blk_v)
    n_rb, n_vb = Rp // blk_r, Vp // blk_v
    vf = jnp.full((1, 1), V if vlim is None else vlim, jnp.int32)
    # Zero cotangent at ignored AND padded rows.
    tflat = tf.reshape(Rp)
    gf = jnp.pad(g.astype(jnp.float32), (0, Rp - R))
    gf = jnp.where(tflat == ignore_index, 0.0, gf).reshape(n_rb, 1, blk_r)
    lsef = jnp.pad(lse.astype(jnp.float32), (0, Rp - R)).reshape(n_rb, 1, blk_r)

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, blk_v=blk_v, V=V),
        out_shape=jax.ShapeDtypeStruct((Rp, dp), jnp.float32),
        grid=(n_rb, n_vb),
        in_specs=[
            pl.BlockSpec((blk_r, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((blk_v, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((blk_r, dp), lambda i, j: (i, 0)),
        interpret=interpret,
    )(xf, wf, vf, tf, lsef, gf)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, blk_v=blk_v, V=V),
        out_shape=jax.ShapeDtypeStruct((Vp, dp), jnp.float32),
        grid=(n_vb, n_rb),
        in_specs=[
            pl.BlockSpec((blk_r, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((blk_v, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((1, 1, blk_r), lambda i, j: (j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((blk_v, dp), lambda i, j: (i, 0)),
        interpret=interpret,
    )(xf, wf, vf, tf, lsef, gf)

    return dx[:R, : x.shape[1]].astype(x.dtype), dw[:V, : w.shape[1]].astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_ce(x, w, targets, ignore_index=0):
    """Exact full-softmax CE over logits = x @ w.T without materializing
    them. Returns per-row losses, 0 at rows where target == ignore_index."""
    loss, _ = fused_linear_ce_fwd(x, w, targets, ignore_index)
    return loss


def _vjp_fwd(x, w, targets, ignore_index):
    loss, lse = fused_linear_ce_fwd(x, w, targets, ignore_index)
    return loss, (x, w, targets, lse)


def _vjp_bwd(ignore_index, res, g):
    x, w, targets, lse = res
    dx, dw = fused_linear_ce_bwd(x, w, targets, lse, g, ignore_index)
    return dx, dw, None


fused_linear_ce.defvjp(_vjp_fwd, _vjp_bwd)


def fused_ce_mean_loss(x, head_weights, targets, ignore_index=0):
    """Shared model-side wrapper: mean fused CE over valid (non-ignored)
    positions — the reference trainers' `sum / max(valid, 1)` convention
    (sasrec.py:124-128). x: (..., d); targets: (...) matching x's leading
    shape; head logits = x @ head_weights.T."""
    d = x.shape[-1]
    per_row = fused_linear_ce(
        x.reshape(-1, d), head_weights, targets.reshape(-1), ignore_index
    )
    valid = (targets.reshape(-1) != ignore_index).astype(jnp.float32)
    return per_row.sum() / jnp.maximum(valid.sum(), 1.0)


# ---------------------------------------------------------------------------
# Vocab-sharded fused CE (tensor parallelism over the head).
#
# Under tp>1 the head weights are vocab-sharded over the "model" mesh axis
# (parallel/shardings.qwen_rules dim 0) — exactly the configuration where a
# fused CE matters most (LCRec's ~150k-row head) and where the dense kernel
# above cannot be GSPMD-partitioned. Inside shard_map each shard runs the
# dense local kernels over its (V/tp, d) slice with offset-mapped targets,
# then the online-softmax accumulators combine across shards with one pmax
# + two psums (flash-style merge of per-shard logsumexps). Loss and grads
# match the replicated fused path to fp32 rounding; dW stays sharded, dx is
# psum-replicated.
#
# Structure note: the custom_vjp sits at the GLOBAL level and its fwd and
# bwd each run their own primal-only shard_map with every cross-shard
# reduction written explicitly. Differentiating *through* a shard_map whose
# replication checking is off mis-scales cotangents of outputs replicated
# over unmentioned axes (observed: dW halved at tp=2), so transposition of
# a shard_map region is deliberately never relied on here.
# ---------------------------------------------------------------------------


def _local_shard_stats(x, w_shard, targets, axis_name, valid_vocab):
    """Per-shard (local_targets, local_vlim, lse_local, target_logit_local).

    Targets are global vocab ids; ids outside this shard's [off, off+Vs)
    window map to -1, which never matches a column (so the shard
    contributes exactly 0.0 to the target-logit sum). The local kernel
    runs with ignore_index=-2 (never matches): row-level ignore semantics
    are applied globally by the caller, on the GLOBAL target id.
    ``valid_vocab`` (global live-vocab limit, or None) becomes the traced
    per-shard column limit clip(valid_vocab - off, 0, Vs).
    """
    local_tgt, vlim = _local_shard_targets(
        w_shard, targets, axis_name, valid_vocab
    )
    # loss_l = lse_l - t_l (no rows zeroed at ignore_index=-2), so the
    # target-logit partial is recoverable without a second kernel.
    loss_l, lse_l = fused_linear_ce_fwd(
        x, w_shard, local_tgt, ignore_index=-2, vlim=vlim
    )
    return local_tgt, vlim, lse_l, lse_l - loss_l


def _tp_shard_map(body, mesh, model_axis, data_axis, in_specs, out_specs):
    from jax.sharding import PartitionSpec as P

    def fix(spec):
        # Drop the data axis from specs when the mesh has no such axis
        # (pure-tp meshes).
        if data_axis is None or data_axis not in mesh.axis_names:
            return P(*(a for a in spec if a != data_axis))
        return spec

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(fix(s) for s in in_specs),
        out_specs=tuple(fix(s) for s in out_specs),
        check_vma=False,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def sharded_fused_linear_ce(x, w, targets, mesh, model_axis="model",
                            data_axis="data", ignore_index=0,
                            valid_vocab=None):
    """Exact full-softmax CE with the head vocab-sharded over
    ``model_axis``.

    Call at the GLOBAL (GSPMD) level: x (R, d) activations, w (Vpad, d)
    head weights laid out P(model_axis, None), targets (R,) global vocab
    ids; rows shard over ``data_axis`` when the mesh has one. Vpad must
    divide by the model-axis size (the trainer's extend_vocab pad_to
    guarantees this) and head pad rows past ``valid_vocab`` (static int)
    are excluded from the softmax, matching mask_vocab_logits /
    w[:valid_vocab] on the replicated path. Returns per-row losses, 0 at
    ignored rows.
    """
    loss, _ = _tp_vjp_fwd(
        x, w, targets, mesh, model_axis, data_axis, ignore_index, valid_vocab
    )
    return loss


def _tp_vjp_fwd(x, w, targets, mesh, model_axis, data_axis, ignore_index,
                valid_vocab):
    from jax.sharding import PartitionSpec as P

    def body(x, w_shard, t):
        _, _, lse_l, t_l = _local_shard_stats(
            x, w_shard, t, model_axis, valid_vocab
        )
        # A shard whose live window is empty (all pad rows) reports
        # lse_l ~ NEG; exp(lse_l - m) underflows to 0 in the merge.
        m = jax.lax.pmax(lse_l, model_axis)
        lse_g = m + jnp.log(jax.lax.psum(jnp.exp(lse_l - m), model_axis))
        t_g = jax.lax.psum(t_l, model_axis)
        t32 = t.astype(jnp.int32)
        loss = jnp.where(t32 == ignore_index, 0.0, lse_g - t_g)
        return loss, lse_g

    loss, lse_g = _tp_shard_map(
        body, mesh, model_axis, data_axis,
        in_specs=(P(data_axis), P(model_axis), P(data_axis)),
        out_specs=(P(data_axis), P(data_axis)),
    )(x, w, targets)
    return loss, (x, w, targets, lse_g)


def _tp_vjp_bwd(mesh, model_axis, data_axis, ignore_index, valid_vocab,
                res, g):
    from jax.sharding import PartitionSpec as P

    x, w, targets, lse_g = res

    def body(x, w_shard, t, lse, g):
        local_tgt, vlim, = _local_shard_targets(
            w_shard, t, model_axis, valid_vocab
        )
        t32 = t.astype(jnp.int32)
        g = jnp.where(t32 == ignore_index, 0.0, g.astype(jnp.float32))
        dx_l, dw_l = fused_linear_ce_bwd(
            x, w_shard, local_tgt, lse, g, ignore_index=-2, vlim=vlim
        )
        # dx: each model shard covers its vocab slice of
        # g*(softmax - onehot) @ W; the full row-grad is their sum.
        dx = jax.lax.psum(dx_l, model_axis)
        # dW: shard-local in the vocab dim (pad rows past vlim get exactly
        # zero, their cols are NEG-masked), but each data shard only saw
        # its batch rows — sum the batch contributions explicitly.
        if data_axis is not None and data_axis in mesh.axis_names:
            dw_l = jax.lax.psum(dw_l, data_axis)
        return dx, dw_l

    dx, dw = _tp_shard_map(
        body, mesh, model_axis, data_axis,
        in_specs=(
            P(data_axis), P(model_axis), P(data_axis), P(data_axis),
            P(data_axis),
        ),
        out_specs=(P(data_axis), P(model_axis)),
    )(x, w, targets, lse_g, g)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


def _local_shard_targets(w_shard, targets, axis_name, valid_vocab):
    """(local_targets, local_vlim) — the offset mapping of
    _local_shard_stats without running the forward kernel."""
    Vs = w_shard.shape[0]
    off = jax.lax.axis_index(axis_name).astype(jnp.int32) * Vs
    t32 = targets.astype(jnp.int32)
    here = (t32 >= off) & (t32 < off + Vs)
    local_tgt = jnp.where(here, t32 - off, -1)
    vlim = (
        None
        if valid_vocab is None
        else jnp.clip(jnp.int32(valid_vocab) - off, 0, Vs)
    )
    return local_tgt, vlim


sharded_fused_linear_ce.defvjp(_tp_vjp_fwd, _tp_vjp_bwd)


def linear_ce_xla(x, w, targets, ignore_index=0):
    """Reference path: materialized logits + CE (what the kernel replaces)."""
    logits = (x.astype(jnp.float32) @ w.T.astype(jnp.float32))
    lse = jax.nn.logsumexp(logits, axis=-1)
    t = jnp.take_along_axis(logits, targets[:, None].astype(jnp.int32), axis=1)[:, 0]
    return jnp.where(targets == ignore_index, 0.0, lse - t)
