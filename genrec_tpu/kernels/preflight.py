"""Compiled-path (Mosaic) validation + microbench for the Pallas kernels.

CI runs the kernels in ``interpret=True`` mode on CPU; this module is the
place where the actual TPU lowering is exercised. Runnable standalone:

    python -m genrec_tpu.kernels.preflight

On a TPU backend it compiles both kernels with ``interpret=False``,
checks them against their XLA references, and times both paths. On any
other backend it reports ``skipped``. Results go to stdout as one JSON
object so bench.py (and humans) can consume them.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _bench_chained(f, x0, *rest, n=512, reps=3):
    """Per-iteration wall-time (ms) of ``n`` data-dependent applications of
    ``f`` looped ON DEVICE (lax.scan carries f's output back as its first
    argument). A remote-tunnel TPU (axon) adds ~60ms of RPC latency per
    dispatch — enough to bury a sub-ms kernel even when unrolled a few
    dozen times — so the loop must be long and live device-side; scan
    compiles the kernel once regardless of n."""
    import jax

    @jax.jit
    def chained(x0, *rest):
        def body(x, _):
            return f(x, *rest), None

        out, _ = jax.lax.scan(body, x0, None, length=n)
        return out

    import jax.numpy as jnp

    def sync(o):
        # Host pull, not block_until_ready: the latter has been observed
        # returning early over the axon tunnel (see bench.py).
        return float(jnp.sum(o))

    sync(chained(x0, *rest))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(chained(x0, *rest))
        times.append((time.perf_counter() - t0) * 1e3 / n)
    return float(np.median(times))


def _rq_cascade_xla(x, codebooks):
    """Plain-XLA residual-quantization cascade (reference for the kernel)."""
    import jax
    import jax.numpy as jnp

    def layer(resid, cb):
        d2 = (
            jnp.sum(resid**2, -1, keepdims=True)
            - 2.0 * jnp.matmul(resid, cb.T, precision=jax.lax.Precision.HIGHEST)
            + jnp.sum(cb**2, -1)
        )
        ids = jnp.argmin(d2, -1)
        return resid - cb[ids], ids

    def scan_fn(resid, cb):
        resid, ids = layer(resid, cb)
        return resid, ids

    resid, ids = jax.lax.scan(scan_fn, x, codebooks)
    return ids.T, x - resid


def run(interpret: bool = False) -> dict:
    """Validate + time both kernels. Returns a JSON-able result dict."""
    import jax
    import jax.numpy as jnp

    from genrec_tpu.kernels.hstu_attention import (
        hstu_attention_pallas,
        hstu_attention_xla,
    )
    from genrec_tpu.kernels.rq_cascade import rq_cascade_pallas

    backend = jax.default_backend()
    res: dict = {"backend": backend, "kernels": {}}
    if backend != "tpu" and not interpret:
        res["skipped"] = "not on TPU; rerun with --interpret to smoke-test"
        return res

    rng = np.random.default_rng(0)

    # --- HSTU fused attention (bench-scale shapes: B4 H4 L200 D64;
    # tiny shapes in interpret mode, where pallas is ~1000x slower) ---
    try:
        B, H, L, D = (2, 2, 50, 32) if interpret else (4, 4, 200, 64)
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
            for _ in range(3)
        )
        ts = jnp.asarray(
            np.cumsum(rng.integers(3600, 2e5, (B, L)), 1), jnp.int32
        )
        pad = jnp.zeros((B, L), bool)
        pt = jnp.asarray(rng.normal(size=(H, 32)) * 0.1, jnp.float32)  # (H, pos buckets)
        tt = jnp.asarray(rng.normal(size=(H, 64)) * 0.1, jnp.float32)  # (H, time buckets)
        pallas_fn = jax.jit(
            lambda *a: hstu_attention_pallas(*a, interpret=interpret)
        )
        xla_fn = jax.jit(hstu_attention_xla)
        got = np.asarray(pallas_fn(q, k, v, ts, pad, pt, tt))
        ref = np.asarray(xla_fn(q, k, v, ts, pad, pt, tt))
        err = float(np.max(np.abs(got - ref)))
        entry = {"max_abs_err": err, "ok": bool(err < 2e-3)}
        if not interpret:
            # The output has q's shape, so it scan-carries back as q.
            entry["pallas_ms"] = _bench_chained(
                hstu_attention_pallas, q, k, v, ts, pad, pt, tt
            )
            entry["xla_ms"] = _bench_chained(
                hstu_attention_xla, q, k, v, ts, pad, pt, tt
            )
        res["kernels"]["hstu_attention"] = entry
    except Exception as e:  # noqa: BLE001 - report, don't crash bench
        res["kernels"]["hstu_attention"] = {"ok": False, "error": repr(e)}

    # --- HSTU fused backward (long-context scale: L=2048 compiled; the
    # grads the training step actually uses) ---
    try:
        from genrec_tpu.kernels.hstu_attention import hstu_attention_bwd_pallas

        B, H, L, D = (2, 2, 50, 32) if interpret else (2, 4, 2048, 64)
        q, k, v, g = (
            jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
            for _ in range(4)
        )
        ts = jnp.asarray(
            np.cumsum(rng.integers(3600, 2e5, (B, L)), 1), jnp.int32
        )
        pad = jnp.zeros((B, L), bool)
        pt = jnp.asarray(rng.normal(size=(H, 32)) * 0.1, jnp.float32)
        tt = jnp.asarray(rng.normal(size=(H, 64)) * 0.1, jnp.float32)

        def xla_bwd(g, q, k, v):
            _, vjp = jax.vjp(
                lambda q, k, v, pt, tt: hstu_attention_xla(q, k, v, ts, pad, pt, tt),
                q, k, v, pt, tt,
            )
            return vjp(g)

        pl_fn = jax.jit(
            lambda g, q, k, v: hstu_attention_bwd_pallas(
                q, k, v, ts, pad, pt, tt, g, interpret=interpret
            )
        )
        got = pl_fn(g, q, k, v)
        ref = xla_bwd(g, q, k, v)
        err = float(
            max(
                np.max(np.abs(np.asarray(a) - np.asarray(b)))
                for a, b in zip(ref, got)
            )
        )
        entry = {"max_abs_err": err, "ok": bool(err < 5e-3), "seq_len": L}
        if not interpret:
            # dq has g's shape: chain it back as the cotangent.
            entry["pallas_ms"] = _bench_chained(
                lambda g, q, k, v: hstu_attention_bwd_pallas(
                    q, k, v, ts, pad, pt, tt, g
                )[0],
                g, q, k, v,
            )
            entry["xla_ms"] = _bench_chained(
                lambda g, q, k, v: xla_bwd(g, q, k, v)[0], g, q, k, v
            )
        res["kernels"]["hstu_attention_bwd"] = entry
    except Exception as e:  # noqa: BLE001
        res["kernels"]["hstu_attention_bwd"] = {"ok": False, "error": repr(e)}

    # --- Fused linear+CE (SASRec-Amazon scale: R=B*L=6400 rows, V~12k
    # items, d=64 — where the materialized (R, V) logits cost ~300MB of
    # HBM traffic per direction) ---
    try:
        from genrec_tpu.kernels.fused_ce import (
            fused_linear_ce,
            fused_linear_ce_fwd,
            linear_ce_xla,
        )

        R, V, D = (256, 1000, 48) if interpret else (6400, 12160, 64)
        x = jnp.asarray(rng.normal(size=(R, D)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(V, D)) * 0.1, jnp.float32)
        tgt = jnp.asarray(rng.integers(0, V, (R,)), jnp.int32)
        got, _ = jax.jit(
            lambda x, w: fused_linear_ce_fwd(x, w, tgt, interpret=interpret)
        )(x, w)
        ref = jax.jit(lambda x, w: linear_ce_xla(x, w, tgt))(x, w)
        err = float(np.max(np.abs(np.asarray(got) - np.asarray(ref))))
        entry = {"max_abs_err": err, "ok": bool(err < 1e-3)}
        if not interpret:
            # Time the TRAINING direction (fwd+bwd): grads wrt x chain
            # back as the next iteration's x.
            entry["pallas_ms"] = _bench_chained(
                lambda x, w: jax.grad(
                    lambda x: fused_linear_ce(x, w, tgt).sum()
                )(x),
                x, w,
            )
            entry["xla_ms"] = _bench_chained(
                lambda x, w: jax.grad(
                    lambda x: linear_ce_xla(x, w, tgt).sum()
                )(x),
                x, w,
            )
        res["kernels"]["fused_linear_ce"] = entry
    except Exception as e:  # noqa: BLE001
        res["kernels"]["fused_linear_ce"] = {"ok": False, "error": repr(e)}

    # --- Vocab-sharded fused CE (the LCRec tp>1 head path): shard_map
    # over a 1-wide "model" axis on whatever devices exist — single-chip
    # this still exercises the full sharded code path (axis_index, the
    # vlim scalar input, psum/pmax merge) under Mosaic compilation. ---
    try:
        from jax.sharding import Mesh

        from genrec_tpu.kernels.fused_ce import sharded_fused_linear_ce

        R, V, D = (256, 1000, 48) if interpret else (6400, 12160, 64)
        x = jnp.asarray(rng.normal(size=(R, D)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(V, D)) * 0.1, jnp.float32)
        tgt = jnp.asarray(rng.integers(0, V, (R,)), jnp.int32)
        live = V - V // 16  # exercise the dynamic vocab limit
        tgt = jnp.minimum(tgt, live - 1)
        n_dev = jax.device_count()
        mesh = Mesh(
            np.array(jax.devices()).reshape(1, n_dev), ("data", "model")
        )
        got = jax.jit(
            lambda x, w: sharded_fused_linear_ce(
                x, w, tgt, mesh, "model", "data", 0, live
            )
        )(x, w)
        ref = jax.jit(lambda x, w: linear_ce_xla(x, w[:live], tgt))(x, w)
        err = float(np.max(np.abs(np.asarray(got) - np.asarray(ref))))
        entry = {"max_abs_err": err, "ok": bool(err < 1e-3), "tp": n_dev}
        if not interpret:
            entry["pallas_ms"] = _bench_chained(
                lambda x, w: jax.grad(
                    lambda x: sharded_fused_linear_ce(
                        x, w, tgt, mesh, "model", "data", 0, live
                    ).sum()
                )(x),
                x, w,
            )
        res["kernels"]["sharded_fused_linear_ce"] = entry
    except Exception as e:  # noqa: BLE001
        res["kernels"]["sharded_fused_linear_ce"] = {"ok": False, "error": repr(e)}

    # --- Paged decode attention (serving-scale: 64 slots x 10 beams,
    # H6 hd64, 16-token pages through a block table) vs the pure-JAX
    # gather fallback that CPU serving runs ---
    try:
        from genrec_tpu.kernels.paged_attention import paged_attention_stats_pallas
        from genrec_tpu.ops.paged import paged_attention_stats

        S, Kb, Hh, hd = (4, 3, 2, 16) if interpret else (64, 10, 6, 64)
        page, Pm = 16, 4
        P = 1 + S * Pm
        q = jnp.asarray(rng.normal(size=(S, Kb, Hh, hd)), jnp.float32)
        kp = jnp.asarray(rng.normal(size=(P, page, Hh, hd)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(P, page, Hh, hd)), jnp.float32)
        bt = jnp.asarray(
            1 + np.arange(S * Pm).reshape(S, Pm), jnp.int32
        )
        sl = jnp.asarray(rng.integers(1, Pm * page + 1, (S,)), jnp.int32)
        pl_fn = jax.jit(
            lambda q: paged_attention_stats_pallas(
                q, kp, vp, bt, sl, interpret=interpret
            )[0]
        )
        ref_fn = jax.jit(
            lambda q: paged_attention_stats(q, kp, vp, bt, sl, use_kernel=False)[0]
        )
        got = np.asarray(pl_fn(q))
        ref = np.asarray(ref_fn(q))
        err = float(np.max(np.abs(got - ref)))
        entry = {"max_abs_err": err, "ok": bool(err < 1e-3)}
        if not interpret:
            # acc has q's leading shape but padded lanes; rebuild q-shaped
            # output for the scan carry by slicing inside the lambda.
            entry["pallas_ms"] = _bench_chained(
                lambda q: paged_attention_stats_pallas(q, kp, vp, bt, sl)[0],
                q,
            )
            entry["xla_ms"] = _bench_chained(
                lambda q: paged_attention_stats(
                    q, kp, vp, bt, sl, use_kernel=False
                )[0],
                q,
            )
        res["kernels"]["paged_attention"] = entry
    except Exception as e:  # noqa: BLE001
        res["kernels"]["paged_attention"] = {"ok": False, "error": repr(e)}

    # --- RQ cascade (rqvae-scale: B2048 D32 L3 K256) ---
    try:
        Bq, Dq, Lq, Kq = (128, 16, 3, 20) if interpret else (2048, 32, 3, 256)
        x = jnp.asarray(rng.normal(size=(Bq, Dq)), jnp.float32)
        cbs = jnp.asarray(rng.normal(size=(Lq, Kq, Dq)), jnp.float32)
        pallas_fn = jax.jit(
            lambda *a: rq_cascade_pallas(*a, blk_b=256, interpret=interpret)
        )
        xla_fn = jax.jit(_rq_cascade_xla)
        ids, qsum = pallas_fn(x, cbs)
        rids, rqsum = xla_fn(x, cbs)
        ids_match = bool(np.array_equal(np.asarray(ids), np.asarray(rids)))
        qerr = float(np.max(np.abs(np.asarray(qsum) - np.asarray(rqsum))))
        entry = {
            "ids_match": ids_match,
            "qsum_max_abs_err": qerr,
            "ok": bool(ids_match and qerr < 1e-3),
        }
        if not interpret:
            # qsum has x's shape, so it scan-carries back as x.
            entry["pallas_ms"] = _bench_chained(
                lambda a, b: rq_cascade_pallas(a, b, blk_b=256)[1], x, cbs
            )
            entry["xla_ms"] = _bench_chained(
                lambda a, b: _rq_cascade_xla(a, b)[1], x, cbs
            )
        res["kernels"]["rq_cascade"] = entry
    except Exception as e:  # noqa: BLE001
        res["kernels"]["rq_cascade"] = {"ok": False, "error": repr(e)}

    res["ok"] = all(k.get("ok") for k in res["kernels"].values())
    return res


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description="Pallas kernel preflight")
    ap.add_argument(
        "--interpret",
        action="store_true",
        help="run in interpreter mode (works off-TPU; no timings)",
    )
    args = ap.parse_args(argv)
    if args.interpret:
        # Interpret mode is a CPU smoke test; do not touch (or hang on)
        # a TPU backend for it. Must run before first device use.
        import jax

        jax.config.update("jax_platforms", "cpu")
    res = run(interpret=args.interpret)
    print(json.dumps(res))
    return 0 if res.get("ok") or "skipped" in res else 1


if __name__ == "__main__":
    raise SystemExit(main())
