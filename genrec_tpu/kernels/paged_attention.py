"""Pallas paged decode-attention kernel (Ragged Paged Attention,
arxiv 2604.15464).

One decode step reads K/V straight from the page pool through the
per-slot block table — the gathered (S, pages*page_size, H, hd)
contiguous copy the pure-JAX fallback materializes (ops/paged.py) never
exists in HBM. The grid walks (slot, head, page); the block table and
sequence lengths ride as SCALAR-PREFETCH operands so each page's
index_map can resolve its pool row before the kernel body runs, and the
softmax accumulates flash-style across the sequentially-executed page
axis (running max / sum / unnormalized accumulator in revisited output
blocks, the same accumulation discipline as the HSTU backward kernel).

Numerics contract == ops/paged.py `_stats_fallback` exactly: masked
positions (token index >= seq_len) are FILLED with -1e9 and stay inside
the softmax, so paged == dense parity survives the kernel path too
(pinned in tests/test_kv_pool.py the way test_hstu_kernel pins the HSTU
kernel against its XLA reference).

Shapes: the page axis is the sublane dimension of the K/V blocks, so
``page_size`` must be a multiple of 8; beams x heads are tiny for the
decode heads, so q/acc blocks are padded up to the (8, 128) fp32 tile in
the wrapper. Off-TPU the kernel runs in interpreter mode (CI parity);
on TPU `kernels.policy.auto_paged_attention` gates it in.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e9


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
            *, page: int, scale: float):
    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)
        # -1e9 start: a fully-masked slot keeps m == -1e9, so every masked
        # score contributes exp(0) == 1 — the fallback's exact behavior
        # (and the dense paths': -1e9 additive fill, not exclusion).
        m_ref[...] = jnp.full(m_ref.shape, NEG, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)

    q = q_ref[0, 0].astype(jnp.float32)  # (Kp, hdp)
    k = k_ref[0, :, 0, :].astype(jnp.float32)  # (page, hdp)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # (Kp, page)

    tok = p * page + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(tok >= sl_ref[s], NEG, scores)

    # m and l live lane-replicated in (Kp, 128) blocks: a lane-1 output
    # block is not tileable, so every lane carries the row's value.
    m_prev = m_ref[0, 0]  # (Kp, 128)
    m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
    e = jnp.exp(scores - m_new[:, :1])  # (Kp, page)
    corr = jnp.exp(m_prev - m_new)  # (Kp, 128), lane-replicated
    l_ref[0, 0] = l_ref[0, 0] * corr + e.sum(axis=1, keepdims=True)
    m_ref[0, 0] = m_new
    acc_ref[0, 0] = acc_ref[0, 0] * corr[:, :1] + jnp.dot(
        e, v, preferred_element_type=jnp.float32
    )


def _kernel_quant(bt_ref, sl_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                  acc_ref, m_ref, l_ref, *, page: int, scale: float):
    """Dequant-in-kernel twin of ``_kernel``: K/V blocks arrive int8 and
    are dequantized per page row (``ks``/``vs`` fp32, lane-replicated to
    128; lane 0 is the value) right before the fp32 dots — the pool is
    never upcast outside the kernel."""
    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)
        m_ref[...] = jnp.full(m_ref.shape, NEG, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)

    q = q_ref[0, 0].astype(jnp.float32)  # (Kp, hdp)
    k = k_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, :, :1]  # (page, hdp)
    v = v_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, :, :1]
    scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

    tok = p * page + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(tok >= sl_ref[s], NEG, scores)

    m_prev = m_ref[0, 0]  # (Kp, 128)
    m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
    e = jnp.exp(scores - m_new[:, :1])  # (Kp, page)
    corr = jnp.exp(m_prev - m_new)
    l_ref[0, 0] = l_ref[0, 0] * corr + e.sum(axis=1, keepdims=True)
    m_ref[0, 0] = m_new
    acc_ref[0, 0] = acc_ref[0, 0] * corr[:, :1] + jnp.dot(
        e, v, preferred_element_type=jnp.float32
    )


def paged_attention_stats_pallas_quantized(q, k_pool, v_pool, block_tables,
                                           seq_lens, interpret: bool | None = None):
    """Quantized-pool kernel path: pools are ``ops.quant.QuantizedKVPool``
    (int8 data (P, page, H, hd) + fp32 scale (P, page)); the per-page-row
    scales ride as their own blocks resolved through the same block-table
    index_map, and dequantization happens inside the kernel body. Same
    (acc, m, l) contract and interpret-mode convention as the fp32 twin,
    pinned against the dequant-after-gather fallback in
    tests/test_quantized.py.
    """
    S, K, H, hd = q.shape
    P, page, _, _ = k_pool.data.shape
    Pm = block_tables.shape[1]
    if page % 8 != 0:
        raise ValueError(f"page_size {page} must be a multiple of 8 (sublanes)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    Kp = _round_up(K, 8)
    hdp = _round_up(hd, 128)
    qp = jnp.pad(q, ((0, 0), (0, Kp - K), (0, 0), (0, hdp - hd)))
    qp = qp.transpose(0, 2, 1, 3)  # (S, H, Kp, hdp)
    kp = jnp.pad(k_pool.data, ((0, 0), (0, 0), (0, 0), (0, hdp - hd)))
    vp = jnp.pad(v_pool.data, ((0, 0), (0, 0), (0, 0), (0, hdp - hd)))
    # Scales lane-pad to (P, page, 128): only lane 0 is read in the
    # kernel, the rest is tiling headroom (a (1, page) block is not
    # lane-tileable). Transient operand, tiny next to the pool.
    ks = jnp.pad(k_pool.scale[:, :, None], ((0, 0), (0, 0), (0, 127)))
    vs = jnp.pad(v_pool.scale[:, :, None], ((0, 0), (0, 0), (0, 127)))

    grid = (S, H, Pm)
    kernel = functools.partial(_kernel_quant, page=page, scale=hd**-0.5)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, Kp, hdp), lambda s, h, p, bt, sl: (s, h, 0, 0)),
            pl.BlockSpec((1, page, 1, hdp),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, h, 0)),
            pl.BlockSpec((1, page, 128),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, 0)),
            pl.BlockSpec((1, page, 1, hdp),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, h, 0)),
            pl.BlockSpec((1, page, 128),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, Kp, hdp), lambda s, h, p, bt, sl: (s, h, 0, 0)),
            pl.BlockSpec((1, 1, Kp, 128), lambda s, h, p, bt, sl: (s, h, 0, 0)),
            pl.BlockSpec((1, 1, Kp, 128), lambda s, h, p, bt, sl: (s, h, 0, 0)),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((S, H, Kp, hdp), jnp.float32),
            jax.ShapeDtypeStruct((S, H, Kp, 128), jnp.float32),
            jax.ShapeDtypeStruct((S, H, Kp, 128), jnp.float32),
        ],
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      qp, kp, ks, vp, vs)

    acc = acc[:, :, :K, :hd].transpose(0, 2, 1, 3)  # (S, K, H, hd)
    m = m[:, :, :K, 0].transpose(0, 2, 1)  # (S, K, H)
    l = l[:, :, :K, 0].transpose(0, 2, 1)
    return acc, m, l


def paged_attention_stats_pallas(q, k_pool, v_pool, block_tables, seq_lens,
                                 interpret: bool | None = None):
    """Kernel twin of ops/paged.py `_stats_fallback`: (acc, m, l) fp32.

    q (S, K, H, hd); pools (P, page, H, hd); block_tables (S, Pm) int32;
    seq_lens (S,) int32. Interpreter mode off-TPU (Mosaic compiles only
    there), matching the HSTU kernel's convention.
    """
    S, K, H, hd = q.shape
    P, page, _, _ = k_pool.shape
    Pm = block_tables.shape[1]
    if page % 8 != 0:
        raise ValueError(f"page_size {page} must be a multiple of 8 (sublanes)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    Kp = _round_up(K, 8)
    hdp = _round_up(hd, 128)
    qp = jnp.pad(q, ((0, 0), (0, Kp - K), (0, 0), (0, hdp - hd)))
    qp = qp.transpose(0, 2, 1, 3)  # (S, H, Kp, hdp)
    kp = jnp.pad(k_pool, ((0, 0), (0, 0), (0, 0), (0, hdp - hd)))
    vp = jnp.pad(v_pool, ((0, 0), (0, 0), (0, 0), (0, hdp - hd)))

    # hd zero-padding leaves q.k dot products unchanged; K(beam) padding
    # rows produce garbage stats that are sliced away below.
    grid = (S, H, Pm)
    kernel = functools.partial(_kernel, page=page, scale=hd**-0.5)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, Kp, hdp), lambda s, h, p, bt, sl: (s, h, 0, 0)),
            # The paged read: index_map resolves the pool row from the
            # prefetched block table — page bt[s, p] of head h.
            pl.BlockSpec((1, page, 1, hdp),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, h, 0)),
            pl.BlockSpec((1, page, 1, hdp),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, Kp, hdp), lambda s, h, p, bt, sl: (s, h, 0, 0)),
            pl.BlockSpec((1, 1, Kp, 128), lambda s, h, p, bt, sl: (s, h, 0, 0)),
            pl.BlockSpec((1, 1, Kp, 128), lambda s, h, p, bt, sl: (s, h, 0, 0)),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((S, H, Kp, hdp), jnp.float32),
            jax.ShapeDtypeStruct((S, H, Kp, 128), jnp.float32),
            jax.ShapeDtypeStruct((S, H, Kp, 128), jnp.float32),
        ],
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32), qp, kp, vp)

    acc = acc[:, :, :K, :hd].transpose(0, 2, 1, 3)  # (S, K, H, hd)
    m = m[:, :, :K, 0].transpose(0, 2, 1)  # (S, K, H)
    l = l[:, :, :K, 0].transpose(0, 2, 1)
    return acc, m, l
