"""Central auto-enable policy for the Pallas kernels.

Every trainer exposing a three-state kernel flag ("auto" / True / False)
resolves "auto" through this module so the policy — and the operational
kill-switch — live in exactly one place.
"""

from __future__ import annotations

import os

import jax


def pallas_disabled() -> bool:
    """GENREC_TPU_DISABLE_PALLAS=1 is the operational kill-switch: the TPU
    watchdog (scripts/tpu_watchdog.sh) sets it when kernel preflight fails
    so a broken Mosaic compile cannot wedge a bench or training run. It
    only affects "auto" resolution; explicit True still opts in."""
    return os.environ.get("GENREC_TPU_DISABLE_PALLAS", "").strip().lower() in (
        "1",
        "true",
    )


def auto_fused_ce(tensor_parallel: int = 1) -> bool:
    """"auto" policy for the fused linear+CE kernel (kernels/fused_ce.py).

    On for single-chip TPU runs only: compiled Mosaic partitioning under
    multi-chip GSPMD is hardware-validated single-chip only (docs/PERF.md),
    and tensor_parallel > 1 vocab-shards the head, which the dense kernel
    cannot partition over (the sharded path is kernels/fused_ce.py
    sharded_fused_linear_ce, wired separately by the trainers).
    """
    return (
        not pallas_disabled()
        and jax.default_backend() == "tpu"
        and jax.device_count() == 1
        and tensor_parallel == 1
    )


def auto_pallas_attention() -> bool:
    """"auto" policy for the fused HSTU attention kernel (fwd + bwd)."""
    return not pallas_disabled() and jax.default_backend() == "tpu"


def auto_paged_attention() -> bool:
    """"auto" policy for the paged decode-attention kernel
    (kernels/paged_attention.py). TPU-only: off-TPU the serving engine and
    the parity tests run the pure-JAX gather fallback in ops/paged.py,
    which is the numerics contract the kernel is pinned against."""
    return not pallas_disabled() and jax.default_backend() == "tpu"


def auto_sharded_fused_ce() -> bool:
    """"auto" policy for the vocab-SHARDED fused CE (LCRec tp>1 head,
    kernels/fused_ce.sharded_fused_linear_ce). No single-chip gate:
    shard_map hands each device a local pallas_call, so GSPMD never has
    to partition the Mosaic call."""
    return not pallas_disabled() and jax.default_backend() == "tpu"
