"""Level 2 of graftlint: repo-specific AST rules (no jax import needed).

Three rule families:

- **layering** — the import-graph rules are GENERATED from the layer map
  in docs/architecture.md (the ASCII diagram: ``L6 Serving``,
  ``L0 Runtime``, ... down to the cross-cutting ``Lx Observability``).
  A package may import same-or-lower layers. Leaf substrates (obs,
  analysis — the ``Lx`` rows) may import NOTHING else inside genrec_tpu:
  they are fed by every layer and must stay importable from every layer
  without cycles. ``configlib`` is declared OPEN (importable from any
  layer): its L5 row in the diagram places the *config surface* above
  trainers, but the package itself is a dependency-free substrate that
  models/trainers use for registration decorators. Extra forbidden
  edges cover dependencies the level ordering alone would allow
  (serving must never import trainers: a serving process must not drag
  the training stack into its image).

- **trace_purity** — inside functions handed to ``jax.jit`` /
  ``jax.lax.scan`` / ``shard_map`` (by name, decorator, or inline
  lambda): ``time.time()``-family calls, ``np.random.*``,
  ``int()/float()/bool()`` coercions of a traced parameter, and Python
  ``if`` on a bare traced parameter. Each of these either bakes a
  trace-time value into the executable (recompile ladder / frozen
  clock) or forces a trace-time concretization error at best.

- **lock_held_blocking** — in the threaded layers (serving/, obs/,
  disagg/): no
  ``Future.result``, ``<queue>.get`` without timeout, ``time.sleep``,
  thread ``join``, or device sync (``block_until_ready`` /
  ``device_get``) while a ``threading.Lock``/``RLock`` is held. The
  batcher/watcher/tracer threads share these locks; a blocking call
  under one is a real deadlock class (the blocked thread holds the lock
  the unblocking thread needs). ``Condition.wait`` is exempt by design —
  it releases the lock — and is not in the blocking set.

Static analysis is conservative by construction: the traced-function
discovery follows names within one module (the repo's idiom — factories
close over models and are jitted in the same scope), and the coercion /
branch rules fire only on direct parameter uses. The fixture tests in
tests/test_analysis.py pin both the trigger and the just-barely-doesn't
side of every rule.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from genrec_tpu.analysis.findings import Finding

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: The Lx rows of the layer map: leaf substrates that import nothing
#: else from genrec_tpu. ``analysis`` itself is held to the same rule.
LEAF_LEVEL = -1.0

#: Importable from any layer (see module docstring).
OPEN_PACKAGES = frozenset({"configlib"})

#: Dependency edges forbidden even though the level ordering allows them
#: (src imports dst): a serving image must not contain the training stack.
FORBIDDEN_EDGES = frozenset({("serving", "trainers")})

#: Top-level driver modules outside the layer discipline (task runners
#: that by design touch every layer).
EXEMPT_MODULES = frozenset({"pipelines"})


# ---------------------------------------------------------------------------
# Layer map: generated from docs/architecture.md
# ---------------------------------------------------------------------------

_LAYER_ROW = re.compile(r"^[│|]\s+L(\d+|x)\b")
_PKG = re.compile(r"genrec_tpu[./](\w+)")


def parse_layer_map(architecture_md: str) -> dict[str, float]:
    """Package -> layer level from the architecture diagram.

    Rows look like ``│ L6  Serving   genrec_tpu/serving/ (...)`` with
    continuation lines (no ``Ln`` label) listing more packages of the
    same layer; ``Lx`` rows map to LEAF_LEVEL. Raises if the diagram
    yields no map at all — the rule must not pass vacuously when the doc
    is restructured.
    """
    level: Optional[float] = None
    mapping: dict[str, float] = {}
    for line in architecture_md.splitlines():
        m = _LAYER_ROW.match(line.strip())
        if m:
            tag = m.group(1)
            level = LEAF_LEVEL if tag == "x" else float(tag)
        elif not line.strip().startswith(("│", "|")):
            level = None  # left the diagram box
        if level is None:
            continue
        for pkg in _PKG.findall(line):
            mapping.setdefault(pkg, level)
    if not mapping:
        raise ValueError(
            "no layer map found in docs/architecture.md — the layering rule "
            "would be vacuous; restore the L0..L6/Lx diagram or update "
            "analysis/lint.py's parser"
        )
    return mapping


def load_layer_map(repo: str = REPO) -> dict[str, float]:
    with open(os.path.join(repo, "docs", "architecture.md")) as f:
        return parse_layer_map(f.read())


# ---------------------------------------------------------------------------
# Per-file AST helpers
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('jax.lax.scan', ...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _genrec_imports(tree: ast.AST, relpath: str = "") -> list[tuple[str, int]]:
    """(imported genrec_tpu package, lineno) for every import in a file,
    including imports nested inside functions (lazy imports are still
    dependency edges — they fire at serve/train time) and RELATIVE
    imports (``from ..parallel import mesh`` is the same edge as the
    absolute spelling; resolved against ``relpath``)."""
    # The file's containing package as dotted parts: genrec_tpu/obs/x.py
    # and genrec_tpu/obs/__init__.py both live in package genrec_tpu.obs.
    pkg_parts = relpath.replace(os.sep, "/").split("/")[:-1] if relpath else []

    def absolute(module: Optional[str], level: int) -> list[str]:
        if level == 0:
            return module.split(".") if module else []
        base = pkg_parts[: len(pkg_parts) - (level - 1)]
        return base + (module.split(".") if module else [])

    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                m = re.match(r"genrec_tpu\.(\w+)", alias.name)
                if m:
                    out.append((m.group(1), node.lineno))
        elif isinstance(node, ast.ImportFrom):
            full = absolute(node.module, node.level)
            if not full or full[0] != "genrec_tpu":
                continue
            if len(full) >= 2:
                out.append((full[1], node.lineno))
            else:
                # from genrec_tpu import X / from .. import X (at the
                # package root): each alias names the package.
                for alias in node.names:
                    out.append((alias.name, node.lineno))
    return out


def _module_package(relpath: str) -> Optional[str]:
    """genrec_tpu/serving/engine.py -> 'serving'; genrec_tpu/pipelines.py
    -> 'pipelines'; files outside the package -> None."""
    parts = relpath.replace(os.sep, "/").split("/")
    if parts[0] != "genrec_tpu" or len(parts) < 2:
        return None
    if len(parts) == 2:
        return os.path.splitext(parts[1])[0]
    return parts[1]


# ---------------------------------------------------------------------------
# Rule: layering
# ---------------------------------------------------------------------------

def check_layering(
    relpath: str,
    tree: ast.AST,
    layers: dict[str, float],
    *,
    open_packages: frozenset = OPEN_PACKAGES,
    forbidden_edges: frozenset = FORBIDDEN_EDGES,
) -> list[Finding]:
    src_pkg = _module_package(relpath)
    if src_pkg is None or src_pkg in EXEMPT_MODULES:
        return []
    src_level = layers.get(src_pkg)
    findings = []
    for dst_pkg, lineno in _genrec_imports(tree, relpath):
        if dst_pkg == src_pkg:
            continue
        edge = (src_pkg, dst_pkg)
        dst_level = layers.get(dst_pkg)
        bad = reason = None
        if edge in forbidden_edges:
            bad = True
            reason = f"the {src_pkg} layer must never import {dst_pkg}"
        elif dst_pkg in EXEMPT_MODULES:
            # Driver modules (pipelines) sit ABOVE the library: they may
            # import everything, but library code importing them would
            # drag every layer into one image through a single hop.
            bad = True
            reason = (
                f"{dst_pkg} is a top-level driver outside the layer "
                "discipline; library code must not import it"
            )
        elif dst_pkg in open_packages:
            # Open substrate: importable from ANY layer, leaves included
            # — checked BEFORE the leaf-source rule so the documented
            # "open for every layer" contract holds for obs/analysis too.
            bad = False
        elif src_level == LEAF_LEVEL:
            # Leaves import NOTHING else from genrec_tpu — not even other
            # leaves (an obs<->analysis edge would be a cycle invisible to
            # the level ordering).
            bad = True
            reason = (
                f"{src_pkg} is a cross-cutting leaf substrate: every layer "
                f"feeds it, so it may import nothing from genrec_tpu "
                f"(invert the dependency — inject the {dst_pkg} callable "
                "from the caller)"
            )
        elif dst_level in (None, LEAF_LEVEL):
            bad = False  # leaf destination, or unmapped (the
            # unmapped_package rule forces a diagram row for new packages)
        elif src_level is not None and dst_level > src_level:
            bad = True
            reason = (
                f"upward import: {src_pkg} (L{src_level:g}) must not depend "
                f"on {dst_pkg} (L{dst_level:g})"
            )
        if bad:
            findings.append(Finding(
                rule="layering",
                where=relpath,
                key=f"{src_pkg}->{dst_pkg}",
                message=f"{relpath}:{lineno}: imports genrec_tpu.{dst_pkg} — "
                        f"{reason}",
                detail={"line": lineno, "src": src_pkg, "dst": dst_pkg},
            ))
    return findings


# ---------------------------------------------------------------------------
# Rule: trace purity
# ---------------------------------------------------------------------------

#: Callee leaf name -> positional-arg indices that are traced functions
#: (fori_loop(lo, hi, body, init) traces args[2]; while_loop traces both
#: the cond and the body).
_TRACING_CALLS = {
    "jit": (0,),
    "scan": (0,),
    "shard_map": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
}
_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.time_ns", "time.perf_counter_ns"}


def _traced_functions(tree: ast.AST) -> list[tuple[str, ast.AST]]:
    """(label, function node) for every function this module hands to a
    tracing transform: @jax.jit-decorated defs, defs whose NAME is passed
    as the first arg to jit/scan/shard_map/..., and inline lambdas."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    traced: dict[int, tuple[str, ast.AST]] = {}

    def mark(label, fn_node):
        if fn_node is not None:
            traced[id(fn_node)] = (label, fn_node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                d = _dotted(target)
                if d.split(".")[-1] == "jit" or (
                    isinstance(dec, ast.Call) and d.endswith("partial")
                    and any(_dotted(a).split(".")[-1] == "jit"
                            for a in dec.args)
                ):
                    mark(node.name, node)
        elif isinstance(node, ast.Call):
            callee = _dotted(node.func).split(".")[-1]
            for argnum in _TRACING_CALLS.get(callee, ()):
                if argnum >= len(node.args):
                    continue
                arg = node.args[argnum]
                if isinstance(arg, ast.Lambda):
                    mark(None, arg)  # labeled by source-order ordinal below
                elif isinstance(arg, ast.Name):
                    mark(arg.id, defs.get(arg.id))
    # Label traced lambdas by SOURCE-ORDER ordinal, not line number: the
    # label flows into the finding fingerprint, which must survive
    # unrelated edits to the file (findings.py contract). Adding a traced
    # lambda earlier in the file shifts later ordinals — rare, and
    # strictly better than every line edit above one churning the
    # baseline.
    lambdas = sorted(
        (node for label, node in traced.values() if label is None),
        key=lambda n: (n.lineno, n.col_offset),
    )
    for i, node in enumerate(lambdas, 1):
        traced[id(node)] = (f"<lambda#{i}>", node)
    return list(traced.values())


def _is_static_read(expr: ast.AST) -> bool:
    """True when a coercion's argument reads only trace-static metadata
    of a traced value — ``int(x.shape[0])``, ``float(x.ndim)``,
    ``bool(len(xs))`` are correct JAX (shapes are static under jit) and
    must not trip the purity rule."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim",
                                                       "size", "dtype"):
            return True
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return True
    return False


def _param_names(fn: ast.AST) -> set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    return set(names)


def check_trace_purity(relpath: str, tree: ast.AST) -> list[Finding]:
    findings = []
    for label, fn in _traced_functions(tree):
        params = _param_names(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in ast.walk(ast.Module(body=body, type_ignores=[])):
            offense = None
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in _CLOCK_CALLS:
                    offense = f"{d}() reads the host clock at TRACE time"
                elif re.match(r"(np|numpy)\.random\.", d):
                    offense = (f"{d}() draws host randomness at TRACE time "
                               "(thread a jax PRNG key instead)")
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in ("int", "float", "bool")
                      and node.args
                      and any(isinstance(n, ast.Name) and n.id in params
                              for n in ast.walk(node.args[0]))
                      and not _is_static_read(node.args[0])):
                    offense = (f"{node.func.id}() coercion of traced "
                               "parameter — concretizes at trace time")
            elif (isinstance(node, ast.If) and isinstance(node.test, ast.Name)
                  and node.test.id in params):
                offense = (f"Python `if {node.test.id}` on a traced "
                           "parameter — use jnp.where / lax.cond")
            if offense:
                findings.append(Finding(
                    rule="trace_purity",
                    where=relpath,
                    key=f"{label}:{offense.split(' ')[0]}",
                    message=f"{relpath}:{node.lineno}: in traced function "
                            f"{label}: {offense}",
                    detail={"line": node.lineno, "function": label},
                ))
    return findings


# ---------------------------------------------------------------------------
# Rule: lock discipline
# ---------------------------------------------------------------------------

#: Directories (package names) the lock rule applies to — the layers
#: with batcher/watcher/tracer thread pools.
LOCKED_PACKAGES = ("serving", "obs", "disagg")

_LOCKISH = re.compile(r"lock", re.I)
_QUEUEISH = re.compile(r"(^|_)(q|queue|queues|inbox|inq|outq)$", re.I)
_THREADISH = re.compile(r"(thread|batcher|watcher|worker|proc)", re.I)


def _is_lock_ctx(expr: ast.AST) -> bool:
    name = _dotted(expr)
    return bool(name) and bool(_LOCKISH.search(name.split(".")[-1]))


def _blocking_offense(node: ast.Call) -> Optional[str]:
    d = _dotted(node.func)
    leaf = d.split(".")[-1]
    recv = ".".join(d.split(".")[:-1])
    recv_leaf = recv.split(".")[-1] if recv else ""
    if d in ("time.sleep",):
        return "time.sleep while holding a lock"
    if leaf == "result":
        # Future.result(timeout) is the same bounded-block pattern the
        # queue.get timeout exemption allows — flag only the unbounded
        # form (no positional timeout, no timeout kwarg).
        bounded = bool(node.args) or any(
            kw.arg == "timeout" for kw in node.keywords
        )
        if not bounded:
            return f"unbounded Future.result ({d}) while holding a lock"
    if leaf == "get" and _QUEUEISH.search(recv_leaf):
        # Bounded or non-blocking reads are fine: get(timeout=...),
        # get(block, timeout), get(False) / get(block=False).
        bounded = (
            any(kw.arg == "timeout" for kw in node.keywords)
            or len(node.args) >= 2
            or any(isinstance(a, ast.Constant) and a.value is False
                   for a in node.args[:1])
            or any(kw.arg == "block"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is False
                   for kw in node.keywords)
        )
        if not bounded:
            return f"{d}() without timeout while holding a lock"
    if leaf == "join" and _THREADISH.search(recv_leaf):
        return f"thread join ({d}) while holding a lock"
    if leaf == "block_until_ready" or d in ("jax.block_until_ready",
                                            "jax.device_get"):
        return f"device sync ({d}) while holding a lock"
    return None


def check_lock_discipline(relpath: str, tree: ast.AST) -> list[Finding]:
    pkg = _module_package(relpath)
    if pkg not in LOCKED_PACKAGES:
        return []
    findings = []

    class _V(ast.NodeVisitor):
        def __init__(self):
            self.ctx: list[str] = []

        def visit_With(self, node: ast.With):
            held = [_dotted(i.context_expr) for i in node.items
                    if _is_lock_ctx(i.context_expr)]
            self.ctx.extend(held)
            for stmt in node.body:
                self.visit(stmt)
            for _ in held:
                self.ctx.pop()

        # A nested def/lambda body runs LATER, not under this lock.
        def visit_FunctionDef(self, node):
            if not self.ctx:
                self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            if not self.ctx:
                self.generic_visit(node)

        def visit_Call(self, node: ast.Call):
            if self.ctx:
                offense = _blocking_offense(node)
                if offense:
                    findings.append(Finding(
                        rule="lock_held_blocking",
                        where=relpath,
                        key=f"{self.ctx[-1]}:{_dotted(node.func)}",
                        message=f"{relpath}:{node.lineno}: {offense} "
                                f"(holding {self.ctx[-1]}) — a blocked "
                                "holder deadlocks every thread waiting on "
                                "this lock",
                        detail={"line": node.lineno, "lock": self.ctx[-1]},
                    ))
            self.generic_visit(node)

    _V().visit(tree)
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def lint_file(
    path: str,
    repo: str = REPO,
    layers: Optional[dict[str, float]] = None,
) -> list[Finding]:
    relpath = os.path.relpath(path, repo)
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError as e:
        return [Finding(rule="syntax_error", where=relpath, key="parse",
                        message=f"{relpath}: does not parse: {e}")]
    findings = []
    if layers is not None:
        findings += check_layering(relpath, tree, layers)
    findings += check_trace_purity(relpath, tree)
    findings += check_lock_discipline(relpath, tree)
    return findings


def iter_source_files(repo: str = REPO) -> Iterable[str]:
    pkg_root = os.path.join(repo, "genrec_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def check_unmapped_packages(repo: str, layers: dict[str, float]) -> list[Finding]:
    """Every genrec_tpu package — and top-level module — must have a row
    in the architecture.md diagram: a name the map does not know is one
    the layering rule cannot constrain (as source OR destination), which
    would make 'machine-enforced layer map' silently false for new code.
    """
    findings = []
    pkg_root = os.path.join(repo, "genrec_tpu")
    for entry in sorted(os.listdir(pkg_root)):
        path = os.path.join(pkg_root, entry)
        if os.path.isdir(path):
            if entry == "__pycache__":
                continue
            name, where = entry, f"genrec_tpu/{entry}/"
        elif entry.endswith(".py") and entry != "__init__.py":
            name, where = entry[:-3], f"genrec_tpu/{entry}"
        else:
            continue
        if name in layers or name in EXEMPT_MODULES:
            continue
        findings.append(Finding(
            rule="unmapped_package",
            where=where,
            key=name,
            message=(
                f"{where} has no row in docs/architecture.md's layer "
                "diagram — the layering rule cannot constrain it; add it "
                "to the diagram (graftlint regenerates the map from the "
                "doc)"
            ),
        ))
    return findings


def lint_repo(repo: str = REPO) -> list[Finding]:
    layers = load_layer_map(repo)
    findings = check_unmapped_packages(repo, layers)
    for path in iter_source_files(repo):
        findings += lint_file(path, repo=repo, layers=layers)
    return findings
