"""Finding type + suppression baseline shared by both graftlint levels.

A `Finding` is one rule violation, with a line-number-free FINGERPRINT
(`rule::where::key`) so the checked-in suppression baseline
(`genrec_tpu/analysis/baseline.json`) survives unrelated edits to the
same file. `where` is an entry-point name (IR level) or a repo-relative
path (AST level); `key` is the rule's stable discriminator (the imported
package, the offending call, the constant's dtype+shape, ...).

The baseline contract (docs/ANALYSIS.md):

- findings whose fingerprint IS in the baseline are reported but do not
  fail CI (pre-existing debt, tracked);
- findings NOT in the baseline fail CI (new debt is blocked);
- baseline fingerprints that no longer match any finding are STALE and
  reported so the baseline shrinks as debt is paid (warn, not fail).

This module imports nothing from genrec_tpu (and no jax): the analysis
package is a leaf substrate like obs — importable from any layer,
importing none of them.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``detail`` carries rule-specific context (shapes, byte counts, line
    numbers) for the human report; it is NOT part of the fingerprint.
    """

    rule: str
    where: str
    key: str
    message: str
    detail: Mapping = dataclasses.field(default_factory=dict, compare=False)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}::{self.where}::{self.key}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "where": self.where,
            "key": self.key,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "detail": dict(self.detail),
        }


#: Rules that may NEVER be suppressed: they mean the analysis itself did
#: not run (a broken manifest builder, an unparseable file). Baselining
#: one would make "the tool is blind here" read as clean forever —
#: save_baseline filters them out and split_by_baseline ignores
#: hand-added fingerprints for them.
NEVER_SUPPRESS = frozenset({"entry_error", "syntax_error"})


def load_baseline(path: str) -> list[str]:
    """Fingerprints from a baseline file; [] when the file is absent."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    fps = data["suppressions"] if isinstance(data, dict) else data
    if not all(isinstance(fp, str) for fp in fps):
        raise ValueError(f"baseline {path} must be a list of fingerprint strings")
    return list(fps)


def save_baseline(path: str, findings: Iterable[Finding], note: str = "") -> None:
    """Write the fingerprints of ``findings`` as the new baseline
    (sorted, deduplicated — diffs stay reviewable). NEVER_SUPPRESS rules
    are excluded: they must keep failing until the analysis runs again."""
    fps = sorted({f.fingerprint for f in findings
                  if f.rule not in NEVER_SUPPRESS})
    payload = {
        "_comment": note or (
            "graftlint suppression baseline: pre-existing findings that do "
            "not fail CI. Regenerate with scripts/graftlint.py "
            "--update-baseline; see docs/ANALYSIS.md."
        ),
        "suppressions": fps,
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def split_by_baseline(
    findings: Sequence[Finding], baseline: Iterable[str]
) -> tuple[list[Finding], list[Finding], list[str]]:
    """(new, baselined, stale_baseline_fingerprints).

    NEVER_SUPPRESS findings are always new, even if someone hand-added
    their fingerprint to the baseline file."""
    base = set(baseline)

    def suppressed(f: Finding) -> bool:
        return f.fingerprint in base and f.rule not in NEVER_SUPPRESS

    new = [f for f in findings if not suppressed(f)]
    old = [f for f in findings if suppressed(f)]
    present = {f.fingerprint for f in findings}
    stale = sorted(base - present)
    return new, old, stale


def summary_metrics(
    findings: Sequence[Finding],
    new: Sequence[Finding],
    baselined: Sequence[Finding],
    stale: Sequence[str],
) -> dict:
    """Flat ``analysis/*`` metrics dict, Tracker/flight-recorder friendly
    (plain str->int, strict-JSON safe), so CI history can chart rule-count
    trends next to goodput."""
    per_rule: dict[str, int] = {}
    for f in findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    out = {
        "analysis/findings": len(findings),
        "analysis/new": len(new),
        "analysis/baselined": len(baselined),
        "analysis/stale_baseline": len(stale),
    }
    for rule, n in sorted(per_rule.items()):
        out[f"analysis/rule/{rule}"] = n
    return out
