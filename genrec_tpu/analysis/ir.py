"""Level 1 of graftlint: lower registered entry points, run IR rules.

Two things live here:

1. **The shared lower/compile harness** the standalone ``scripts/check_*``
   checks are built on (CLI conventions, platform pinning, optimized-HLO
   lowering, verdict emission, docs/PERF.md notes, out/ artifacts). The
   five check scripts each used to carry a private copy of this plumbing;
   they now import it, keeping their CLIs and verdict JSON bit-compatible.

2. **Composable IR rules** over a compile-manifest entry
   (analysis/manifest.py):

   - ``constant_bake``   — literals over a byte threshold embedded in the
     executable (the baked trie today; a million-item catalog tomorrow).
     Catalog-sized data must arrive as a runtime operand, or every
     catalog change recompiles and executable size scales with corpus.
   - ``missing_donation`` — entry argnums declared dead-after-call
     (``BuiltEntry.expect_donated``) that the jit does not donate: one
     dead copy of the buffer stays live across the call (wasted HBM equal
     to the buffer size).
   - ``f64_op``          — double-precision tensors in the optimized HLO
     (silent upcasts double memory traffic and are 10-30x slower on TPU).
   - ``host_transfer_in_loop`` — callbacks/infeed/outfeed inside a
     scan/while body: a device loop that syncs to host every iteration.

Rules read three artifacts of one trace: the jaxpr (host transfers), the
lowering's ``args_info`` (donation — visible on every backend, including
CPU where XLA itself ignores donation), and the optimized HLO text
(constants, dtypes).

jax is imported inside functions, never at module scope: the AST level
and the CLI plumbing must stay importable without pulling in a backend.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
from typing import Optional, Sequence

from genrec_tpu.analysis.findings import Finding
from genrec_tpu.analysis.manifest import BuiltEntry

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Global default for the constant-bake threshold (bytes). Entries can
#: pin a tighter one (BuiltEntry.max_const_bytes); graftlint exposes
#: --max-const-bytes for one-off sweeps.
DEFAULT_MAX_CONST_BYTES = 64 * 1024


# ---------------------------------------------------------------------------
# Shared check-script harness (CLI / lowering / verdict conventions)
# ---------------------------------------------------------------------------

def check_args(argv=None, *, small_help: str = "tiny shapes for fast CI runs",
               note_help: str = "append the verdict to docs/PERF.md",
               extra: Optional[Sequence[tuple]] = None) -> argparse.Namespace:
    """The standard check-script CLI: --write-note / --small / --platform.

    ``extra`` adds script-specific flags as (args_tuple, kwargs_dict)
    pairs. Parsing happens BEFORE jax is imported (scripts pin the
    platform after import via :func:`pin_platform`).
    """
    ap = argparse.ArgumentParser()
    ap.add_argument("--write-note", action="store_true", help=note_help)
    ap.add_argument("--small", action="store_true", help=small_help)
    ap.add_argument("--platform", default=None)
    for args, kwargs in extra or ():
        ap.add_argument(*args, **kwargs)
    return ap.parse_args(argv)


def optimized_hlo(fn, *args, **jit_kwargs) -> str:
    """Optimized HLO text of ``fn(*args)`` as ONE jit program.

    ``fn`` may already be jitted (has ``.lower``) — jit_kwargs must then
    be empty — or a plain callable that gets wrapped here. Compiling is
    itself an assertion: a function that cannot lower/compile as a single
    program raises instead of returning.
    """
    import jax

    if not hasattr(fn, "lower"):
        fn = jax.jit(fn, **jit_kwargs)
    elif jit_kwargs:
        raise ValueError("fn is already jitted; jit_kwargs would be ignored")
    return fn.lower(*args).compile().as_text()


def emit_verdict(verdict: dict) -> None:
    """The one-JSON-line-on-stdout contract of scripts/ci_checks.sh."""
    print(json.dumps(verdict))


def append_perf_note(note: str, repo: str = REPO) -> None:
    with open(os.path.join(repo, "docs", "PERF.md"), "a") as f:
        f.write(note)


def dump_artifact(name: str, text: str, repo: str = REPO) -> str:
    """Write a debug artifact under out/ (e.g. the offending HLO)."""
    out_dir = os.path.join(repo, "out")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        f.write(text)
    return path


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_CONST_RE = re.compile(r"\b(\w+)\[([\d,]*)\]\S*\s+constant\(")


def hlo_constants(hlo: str) -> list[dict]:
    """Every literal in an HLO module as {dtype, shape, bytes, line}."""
    out = []
    for line in hlo.splitlines():
        m = _CONST_RE.search(line)
        if not m:
            continue
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _DTYPE_BYTES:
            continue
        shape = tuple(int(d) for d in dims.split(",") if d)
        n_bytes = _DTYPE_BYTES[dtype] * (math.prod(shape) if shape else 1)
        out.append({"dtype": dtype, "shape": shape, "bytes": n_bytes,
                    "line": line.strip()})
    return out


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

_LOOP_PRIMS = {"scan", "while"}
_HOST_PRIMS = {"pure_callback", "io_callback", "debug_callback",
               "infeed", "outfeed"}


def _subjaxprs(params: dict):
    for val in params.values():
        if hasattr(val, "jaxpr"):  # ClosedJaxpr
            yield val.jaxpr
        elif hasattr(val, "eqns"):  # raw Jaxpr
            yield val
        elif isinstance(val, (tuple, list)):
            for item in val:
                if hasattr(item, "jaxpr"):
                    yield item.jaxpr
                elif hasattr(item, "eqns"):
                    yield item


def host_ops_in_loops(jaxpr) -> list[dict]:
    """Host-transfer primitives that execute inside a scan/while body.

    A callback at a program's top level is one host sync per call —
    sometimes a legitimate choice. The same callback inside a loop body
    is a host round-trip per iteration, which serializes the loop on
    host latency; that is the rule.
    """
    hits: list[dict] = []

    def walk(jx, in_loop: bool):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if in_loop and name in _HOST_PRIMS:
                hits.append({"primitive": name})
            child_in_loop = in_loop or name in _LOOP_PRIMS
            for sub in _subjaxprs(eqn.params):
                walk(sub, child_in_loop)

    walk(jaxpr, False)
    return hits


# ---------------------------------------------------------------------------
# IR rules over a manifest entry
# ---------------------------------------------------------------------------

def _leaf_bytes(arg_info) -> int:
    import numpy as np

    return (int(math.prod(arg_info.shape or (1,)))
            * np.dtype(arg_info.dtype).itemsize)


def analyze_entry(
    name: str,
    built: BuiltEntry,
    max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
) -> tuple[list[Finding], dict]:
    """Run every IR rule over one built entry.

    Returns (findings, stats). One trace feeds all rules: the jaxpr
    (host transfers), the lowering (donation), the compiled text
    (constants, dtypes).
    """
    import jax

    findings: list[Finding] = []
    traced = built.fn.trace(*built.args)
    lowered = traced.lower()

    # -- donation audit ------------------------------------------------------
    args_info = lowered.args_info[0]
    for argnum in built.expect_donated:
        leaves = jax.tree_util.tree_leaves(args_info[argnum])
        undonated = [l for l in leaves if not l.donated]
        if undonated:
            wasted = sum(_leaf_bytes(l) for l in undonated)
            findings.append(Finding(
                rule="missing_donation",
                where=name,
                key=f"arg{argnum}",
                message=(
                    f"{name}: argument {argnum} is dead after the call but "
                    f"{len(undonated)}/{len(leaves)} of its buffers are not "
                    f"donated — ~{wasted / 1e6:.2f} MB of HBM held as a dead "
                    "copy across the step (donate_argnums)"
                ),
                detail={"argnum": argnum, "undonated_buffers": len(undonated),
                        "wasted_bytes": wasted},
            ))

    hlo = lowered.compile().as_text()

    # -- constant bake -------------------------------------------------------
    threshold = (
        built.max_const_bytes
        if built.max_const_bytes is not None else max_const_bytes
    )
    constants = hlo_constants(hlo)
    big: dict[str, dict] = {}
    for const in constants:
        if const["bytes"] <= threshold:
            continue
        key = f"{const['dtype']}{list(const['shape'])}"
        slot = big.setdefault(key, {**const, "count": 0})
        slot["count"] += 1
    for key, const in sorted(big.items()):
        findings.append(Finding(
            rule="constant_bake",
            where=name,
            key=key,
            message=(
                f"{name}: {const['count']} literal(s) of shape "
                f"{const['dtype']}{list(const['shape'])} "
                f"({const['bytes'] / 1e6:.2f} MB each) baked into the "
                f"executable (threshold {threshold} B) — pass catalog-sized "
                "data as a runtime operand, or every refresh recompiles"
            ),
            detail={"bytes": const["bytes"], "count": const["count"],
                    "threshold": threshold},
        ))

    # -- dtype discipline ----------------------------------------------------
    if not built.allow_f64:
        f64_lines = [l.strip() for l in hlo.splitlines()
                     if re.search(r"\bf64\[|\bc128\[", l)]
        if f64_lines:
            findings.append(Finding(
                rule="f64_op",
                where=name,
                key="f64",
                message=(
                    f"{name}: {len(f64_lines)} double-precision op(s) in the "
                    "optimized HLO — a silent upcast somewhere in the entry "
                    f"(first: {f64_lines[0][:120]})"
                ),
                detail={"count": len(f64_lines), "first": f64_lines[0][:200]},
            ))

    # -- host transfers in loop bodies ---------------------------------------
    hits = host_ops_in_loops(traced.jaxpr.jaxpr)
    if hits:
        prims = sorted({h["primitive"] for h in hits})
        findings.append(Finding(
            rule="host_transfer_in_loop",
            where=name,
            key=",".join(prims),
            message=(
                f"{name}: {len(hits)} host-transfer op(s) ({', '.join(prims)}) "
                "inside a scan/while body — the device loop round-trips to "
                "host every iteration"
            ),
            detail={"count": len(hits), "primitives": prims},
        ))

    stats = {
        "hlo_bytes": len(hlo),
        "n_constants": len(constants),
        "const_threshold": threshold,
    }
    return findings, stats


def analyze_manifest(
    entries,
    max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
    on_error: str = "finding",
) -> tuple[list[Finding], dict]:
    """Run the IR rules over every manifest entry.

    A builder or compile that raises becomes an ``entry_error`` finding
    (the manifest itself is load-bearing: a silently skipped entry would
    read as clean) unless ``on_error='raise'``.
    """
    findings: list[Finding] = []
    stats: dict = {}
    for name, entry in sorted(entries.items()):
        try:
            built = entry.build()
            entry_findings, entry_stats = analyze_entry(
                name, built, max_const_bytes=max_const_bytes
            )
        except Exception as e:  # noqa: BLE001 — reported, never swallowed
            if on_error == "raise":
                raise
            findings.append(Finding(
                rule="entry_error",
                where=name,
                key=type(e).__name__,
                message=f"{name}: entry failed to build/lower: {e!r:.300}",
                detail={"error": repr(e)[:500]},
            ))
            stats[name] = {"error": repr(e)[:200]}
        else:
            findings.extend(entry_findings)
            stats[name] = entry_stats
    return findings, stats
