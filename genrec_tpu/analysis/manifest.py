"""Compile manifest: the registry of jitted entry points graftlint lowers.

The IR level of graftlint (analysis/ir.py) cannot discover "every
executable the fleet compiles" by static inspection — jit sites are
buried in trainer factories and serving warmup. Instead, the modules
that OWN an entry point register a small-shape builder here:

    from genrec_tpu.analysis.manifest import BuiltEntry, register_entry

    @register_entry("train/sasrec_packed_step", tags=("train",))
    def _entry() -> BuiltEntry:
        ...build a tiny model/state...
        return BuiltEntry(fn=jax.jit(step, donate_argnums=0),
                          args=(state, batch), expect_donated=(0,))

Registration is import-time cheap (the decorator stores the builder;
nothing is built or traced until graftlint calls it), so trainers and
serving heads can register unconditionally at module scope. The builder
must jit EXACTLY the way production does (same donate_argnums, same
wrapper factories) — the donation audit checks the declared donation of
the built fn, so a builder that re-jits with its own flags would audit
itself instead of the production path.

``expect_donated`` lists the argnums whose buffers are dead after the
call in production (train state consumed by the step, decode slot state
overwritten by the write-back). The donation audit reports any of these
that the jit does NOT donate as wasted HBM (one dead copy of the buffer
kept alive across the call).

This module imports nothing from genrec_tpu (and no jax at module
scope): like obs, the analysis package is importable from every layer.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Callable, Mapping, Optional, Sequence


@dataclasses.dataclass
class BuiltEntry:
    """One lowered-and-analyzable entry point, produced by a builder.

    ``fn`` must be a jitted callable (supports ``.lower``/``.trace``);
    ``args`` may mix concrete arrays and ShapeDtypeStructs.
    """

    fn: Any
    args: tuple
    expect_donated: tuple = ()
    allow_f64: bool = False
    #: Per-entry constant-bake threshold override (bytes). Entries whose
    #: CI shapes shrink a production-sized constant below the global
    #: threshold pin a tighter one so the rule still bites (the same
    #: self-test discipline as the check_*_hlo regex self-tests).
    max_const_bytes: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    name: str
    tags: tuple
    build: Callable[[], BuiltEntry]
    provider: str  # module that registered it, for the report


_REGISTRY: dict[str, EntryPoint] = {}

#: Modules that register entries at import time. graftlint imports these;
#: adding an entry point elsewhere means adding its module here (see
#: docs/ANALYSIS.md "Registering a new entry point").
DEFAULT_PROVIDERS = (
    "genrec_tpu.trainers.sasrec_trainer",
    "genrec_tpu.trainers.tiger_trainer",
    "genrec_tpu.serving.heads",
)


def register_entry(name: str, *, tags: Sequence[str] = ()):
    """Decorator: register ``builder`` as compile-manifest entry ``name``.

    Re-registration under the same name overwrites (idempotent module
    reloads in tests), returns the builder unchanged.
    """

    def deco(builder: Callable[[], BuiltEntry]):
        _REGISTRY[name] = EntryPoint(
            name=name,
            tags=tuple(tags),
            build=builder,
            provider=getattr(builder, "__module__", "?"),
        )
        return builder

    return deco


def registered_entries() -> Mapping[str, EntryPoint]:
    """The entries registered so far (import providers first)."""
    return dict(_REGISTRY)


def load_default_entries(
    providers: Sequence[str] = DEFAULT_PROVIDERS,
) -> Mapping[str, EntryPoint]:
    """Import every provider module (running their register_entry
    decorators) and return the populated manifest."""
    for mod in providers:
        importlib.import_module(mod)
    return registered_entries()
