"""graftlint: two-level static analysis for the repo's hard invariants.

Level 1 (analysis/ir.py) lowers the compile-manifest entry points
(analysis/manifest.py — populated by trainers and serving heads) and
runs IR rules over the jaxpr / optimized HLO: constant bake, donation
audit, f64 discipline, host transfers inside device loops. Level 2
(analysis/lint.py) is an AST linter: architecture.md-derived layering,
trace purity, lock-held blocking calls.

Driver: ``python scripts/graftlint.py`` (one JSON verdict line, rc 0/1,
suppression baseline in analysis/baseline.json). Rule catalog and
workflows: docs/ANALYSIS.md.

Like obs, this package is a leaf substrate: importable from every
layer, importing none of them (and no jax at module scope — providers
register builders, not built entries).
"""

from genrec_tpu.analysis.findings import (
    Finding,
    load_baseline,
    save_baseline,
    split_by_baseline,
    summary_metrics,
)
from genrec_tpu.analysis.manifest import (
    BuiltEntry,
    EntryPoint,
    load_default_entries,
    register_entry,
    registered_entries,
)

__all__ = [
    "Finding",
    "load_baseline",
    "save_baseline",
    "split_by_baseline",
    "summary_metrics",
    "BuiltEntry",
    "EntryPoint",
    "load_default_entries",
    "register_entry",
    "registered_entries",
]
