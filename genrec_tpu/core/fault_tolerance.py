"""Step-granular fault tolerance: interrupt anywhere, resume exactly.

Three pieces, composed by `trainers.packed_loop.PackedTrainLoop`:

1. **Resume points** — one checkpoint record keyed by GLOBAL STEP holding
   the full TrainState *plus the serialized data-iterator state* (epoch,
   next batch index, data seed, prefetch depth). Because the whole input
   pipeline is deterministic in ``(seed, epoch)`` — the per-epoch packer
   permutation (`data.batching.pack_examples(seed=(seed, epoch))`), the
   shuffle (`batch_iterator(seed=..., epoch=...)`), and the prefetcher
   (a pure read-ahead whose unconsumed batches are regenerated on
   resume) — the cursor (epoch, next_batch) pins the exact next batch,
   and a resumed run replays nothing and skips nothing: per-step losses
   and final params match an uninterrupted run bit-for-bit on a fixed
   backend (<=1e-5 fp32 across backends).

2. **Integrity-ladder restore** — `resume_exact` walks retained resume
   points newest-first through `CheckpointManager.restore_latest_valid`,
   quarantining truncated/garbled/structure-mismatched steps instead of
   crashing.

3. **`NonFiniteMonitor`** — host-side policy for the jitted non-finite
   guard in `core.harness.make_train_step`: dump the offending batch to
   disk with step metadata, abort after N CONSECUTIVE skipped steps (the
   streak itself lives on device in ``TrainState.nonfinite_count``, so
   it is checkpointed and survives resume). The check is deferred by one
   step so reading the flag never stalls async dispatch: step N's flag is
   read only after step N+1 has been dispatched.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable

import jax
import numpy as np

from genrec_tpu.core.checkpoint import (
    CheckpointManager,
    CheckpointMismatchError,
    _refuse_resume_below_stale_steps,
    stale_refusal_message,
)

# Version tag for the resume-point record; bump on layout change. The
# check runs as a rung of the integrity ladder: a record with a foreign
# tag is skipped IN PLACE (left on disk for the code version that wrote
# it). Foreign records BELOW the chosen restore point are harmless; if
# any remain ABOVE it, resume refuses loudly — orbax silently drops
# saves keyed below its retained latest, so continuing would checkpoint
# nothing (move the newer step dirs aside to roll back).
_FORMAT = 1


@dataclasses.dataclass
class ResumePoint:
    """Deserialized cursor: continue ``epoch`` at batch ``next_batch``."""

    state: Any
    epoch: int
    next_batch: int
    global_step: int


def _cursor_arrays(
    epoch: int, next_batch: int, global_step: int, data_seed: int,
    prefetch_depth: int,
) -> dict[str, np.ndarray]:
    return {
        "format": np.asarray(_FORMAT, np.int32),
        "epoch": np.asarray(epoch, np.int32),
        # Batches CONSUMED this epoch == index of the next batch to run.
        "next_batch": np.asarray(next_batch, np.int32),
        # Loop-iteration counter (can exceed state.step when the
        # non-finite guard skipped updates).
        "global_step": np.asarray(global_step, np.int64),
        # The base data seed; (data_seed, epoch) derives the packer
        # permutation and the shuffle. Stored to detect a resume launched
        # with a different seed (which would silently break exactness).
        "data_seed": np.asarray(data_seed, np.int64),
        # Unconsumed read-ahead at save time. Always 0 in the record: the
        # prefetcher is stateless read-ahead, so those batches are simply
        # regenerated — recorded for format completeness/forward-compat.
        "prefetch_depth": np.asarray(prefetch_depth, np.int32),
    }


def _composite_like(state_like: Any) -> dict[str, Any]:
    return {"state": state_like, "cursor": _cursor_arrays(0, 0, 0, 0, 0)}


def save_resume_point(
    ckpt: CheckpointManager,
    state: Any,
    *,
    epoch: int,
    next_batch: int,
    global_step: int,
    data_seed: int,
    wait: bool = False,
) -> None:
    """Write a step-keyed resume point (TrainState + iterator cursor).

    Periodic saves stay async (orbax snapshots to host and commits on a
    background thread); a preemption save passes ``wait=True`` so the
    record is durable before the process exits the grace window."""
    ckpt.save(
        global_step,
        {
            "state": state,
            "cursor": _cursor_arrays(epoch, next_batch, global_step, data_seed, 0),
        },
    )
    if wait:
        ckpt.wait()


def resume_exact(
    ckpt: CheckpointManager | None,
    state_like: Any,
    place_fn: Callable[[Any], Any] | None = None,
    *,
    data_seed: int,
    logger=None,
) -> ResumePoint | None:
    """Restore the newest VALID resume point, or None for a fresh start.

    Corrupt steps are quarantined by the integrity ladder. A stored
    data seed differing from the configured one is an error: the shuffle
    and packer permutations would diverge and the 'exact' resume would
    silently replay different data.

    Multi-host: the restore runs through
    `CheckpointManager.restore_latest_valid_consensus` — after each host
    runs the ladder locally, the fleet allgathers newest-valid steps and
    every host restores the SAME step (or the job aborts with a per-host
    validity report), so a checkpoint truncated on one host can never
    silently fork the replicated training state."""
    if ckpt is None:
        return None
    if jax.process_count() == 1 and ckpt.latest_step() is None:
        # Multi-process runs must NOT take this shortcut: one host with
        # an empty directory returning early while another enters the
        # consensus collectives would deadlock — the mixed
        # empty/non-empty case is the consensus pass's job to report.
        return None

    restored, step = _restore_resume_point_consensus(ckpt, state_like)
    # Foreign records retained ABOVE the restore point would silently
    # swallow every future save (orbax refuses keys below its latest):
    # refuse loudly before burning compute on an unsaveable run. On a
    # fleet the decision must be COLLECTIVE — one host raising while its
    # peers enter training would strand the fleet at its next collective
    # — so any host's stale steps abort every host.
    if jax.process_count() > 1:
        from genrec_tpu.parallel.mesh import allgather_host_ints

        # Another host's consensus pass may have quarantined steps in a
        # shared directory since this manager last scanned.
        ckpt.reload()
        stale = [
            s for s in ckpt.all_steps() if step is None or s > step
        ]
        counts = allgather_host_ints([len(stale)])[:, 0]
        if counts.max() > 0:
            report = ", ".join(
                f"p{i}={int(c)}" for i, c in enumerate(counts)
            )
            raise RuntimeError(stale_refusal_message(
                ckpt.directory,
                f"stale-step counts per host: {report}; "
                f"local stale steps {stale}",
                "resume on any host",
            ))
    else:
        _refuse_resume_below_stale_steps(ckpt, step)
    if restored is None:
        if logger is not None:
            logger.warning("no valid resume point survived the integrity ladder")
        return None
    cursor = restored["cursor"]
    if int(cursor["data_seed"]) != int(data_seed):
        raise ValueError(
            f"resume point was written with data seed {int(cursor['data_seed'])} "
            f"but this run uses {int(data_seed)}: refusing an inexact resume"
        )
    state = restored["state"]
    if place_fn is not None:
        state = place_fn(state)
    point = ResumePoint(
        state=state,
        epoch=int(cursor["epoch"]),
        next_batch=int(cursor["next_batch"]),
        global_step=int(cursor["global_step"]),
    )
    if logger is not None:
        logger.info(
            f"resumed at epoch {point.epoch} batch {point.next_batch} "
            f"(global step {point.global_step}, checkpoint step {step})"
        )
    return point


def _restore_resume_point_consensus(ckpt: CheckpointManager, state_like: Any):
    """Walk the integrity ladder over COMPOSITE resume-point records
    (consensus on multi-host), rejecting any whose cursor format this
    code version cannot interpret. The one restore preamble shared by
    `resume_exact` and `restore_for_eval` — a `_FORMAT` bump edited in
    only one of them would let eval and resume disagree on which records
    are restorable."""

    def check_format(restored, step):
        got = int(restored["cursor"]["format"])
        if got != _FORMAT:
            raise CheckpointMismatchError(
                f"step {step}: resume-point format {got} != {_FORMAT} "
                "(written by a different code version)"
            )

    return ckpt.restore_latest_valid_consensus(
        _composite_like(state_like), extra_validate=check_format
    )


def restore_for_eval(
    ckpt: CheckpointManager | None,
    state_like: Any,
    place_fn: Callable[[Any], Any] | None = None,
    *,
    logger=None,
) -> tuple[Any, int | None]:
    """Restore the newest valid model state for a PURE EVALUATION run.

    eval_only consumes no training data, so none of `resume_exact`'s
    exactness preconditions apply: the stored data seed is ignored and
    stale foreign records above the restore point do not refuse (no save
    will ever be keyed below them). Walks the step-granular resume
    points through the integrity ladder first (consensus on multi-host,
    so every host evaluates the same params); single-process runs fall
    back to bare pre-PR4 TrainState records. Returns ``(state, step)``,
    or ``(state_like, None)`` when nothing restores.
    """
    if ckpt is None:
        return state_like, None
    if jax.process_count() == 1 and ckpt.latest_step() is None:
        return state_like, None

    restored, step = _restore_resume_point_consensus(ckpt, state_like)
    if restored is not None:
        state = restored["state"]
    elif jax.process_count() == 1:
        # Pre-PR4 bare TrainState records (epoch-keyed, single-host).
        restored, step = ckpt.restore_latest_valid(state_like)
        if restored is None:
            return state_like, None
        state = restored
    else:
        return state_like, None
    if place_fn is not None:
        state = place_fn(state)
    if logger is not None:
        logger.info(f"eval_only: restored checkpoint step {step}")
    return state, step


class NonFiniteLossError(RuntimeError):
    """Raised after max_consecutive non-finite steps: the run is diverging
    structurally, not hitting a one-off bad batch."""


class NonFiniteMonitor:
    """Host policy for the jitted non-finite guard (core.harness).

    `observe` is called once per step with the step's metrics and the
    on-device batch; it CHECKS the PREVIOUS step's flag (deferred by one
    step, so the device scalar it reads is already computed and the read
    never stalls dispatch of the current step). On a flagged step the
    batch is dumped to ``<dump_dir>/nonfinite_step<g>.npz`` with step
    metadata, and once the device-side consecutive streak
    (``metrics["nonfinite_count"]``) reaches ``max_consecutive`` the run
    aborts with `NonFiniteLossError`. Call `flush()` at epoch end /
    before a preemption save to check the last in-flight step."""

    def __init__(self, dump_dir: str | None, max_consecutive: int = 3,
                 logger=None):
        self.dump_dir = dump_dir
        self.max_consecutive = max_consecutive
        self.logger = logger
        self.dumped: list[str] = []
        # Skipped-step tally for goodput accounting (obs/goodput.py):
        # flags are read one step late, so the LOOP cannot count them
        # without stalling dispatch — the monitor is where they surface.
        self.skipped_steps = 0
        self._pending: tuple[int, int, dict, Any] | None = None

    @classmethod
    def for_run(cls, save_dir_root: str | None, logger=None,
                max_consecutive: int = 3) -> "NonFiniteMonitor":
        """Monitor with the standard dump location for a trainer run
        (``<save_dir_root>/nonfinite/``; no dumps without a save dir)."""
        return cls(
            os.path.join(save_dir_root, "nonfinite") if save_dir_root else None,
            max_consecutive, logger,
        )

    def observe(self, global_step: int, epoch: int, metrics: dict, batch) -> None:
        prev, self._pending = self._pending, (global_step, epoch, metrics, batch)
        if prev is not None:
            self._check(*prev)

    def flush(self) -> None:
        prev, self._pending = self._pending, None
        if prev is not None:
            self._check(*prev)

    def _check(self, global_step: int, epoch: int, metrics: dict, batch) -> None:
        if "nonfinite" not in metrics or not float(metrics["nonfinite"]):
            return
        from genrec_tpu.obs.flight_recorder import get_flight_recorder

        streak = int(float(metrics.get("nonfinite_count", 1.0)))
        self.skipped_steps += 1
        path = self._dump(global_step, epoch, metrics, batch)
        recorder = get_flight_recorder()
        recorder.record(
            "nonfinite_step", step=global_step, epoch=epoch, streak=streak,
            loss=float(metrics["loss"]), dump=path,
        )
        if self.logger is not None:
            self.logger.warning(
                f"non-finite loss/grad at step {global_step} (epoch {epoch}): "
                f"optimizer update skipped (streak {streak}/"
                f"{self.max_consecutive})"
                + (f", batch dumped to {path}" if path else "")
            )
        if streak >= self.max_consecutive:
            recorder.record(
                "nonfinite_abort", step=global_step, epoch=epoch,
                streak=streak, max_consecutive=self.max_consecutive,
            )
            recorder.dump(reason="nonfinite_abort")
            raise NonFiniteLossError(
                f"{streak} consecutive non-finite steps (last: step "
                f"{global_step}, epoch {epoch})"
                + (f"; offending batches dumped under {self.dump_dir}" if path else "")
            )

    def _dump(self, global_step: int, epoch: int, metrics: dict, batch) -> str | None:
        if self.dump_dir is None:
            return None
        # Process-suffixed filename: hosts sharing a filesystem dump the
        # same flagged step concurrently and must not clobber each
        # other's post-mortem artifacts.
        suffix = f"_p{jax.process_index()}" if jax.process_count() > 1 else ""
        os.makedirs(self.dump_dir, exist_ok=True)
        payload: dict[str, np.ndarray] = {
            "global_step": np.asarray(global_step, np.int64),
            "epoch": np.asarray(epoch, np.int64),
            "loss": np.asarray(float(metrics["loss"]), np.float64),
            "grad_norm": np.asarray(float(metrics["grad_norm"]), np.float64),
        }
        for key, leaf in batch.items():
            try:
                payload[f"batch/{key}"] = np.asarray(leaf)
            except Exception:
                # Multi-host: a non-fully-addressable shard can't be
                # materialized here; the metadata alone still localizes
                # the bad step for offline repro.
                continue
        path = os.path.join(
            self.dump_dir, f"nonfinite_step{global_step}{suffix}.npz"
        )
        np.savez(path, **payload)
        self.dumped.append(path)
        return path
