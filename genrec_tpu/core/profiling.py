"""Profiling / tracing hooks.

The reference has NO tracing or profiling at all (SURVEY.md §5.1 — tqdm
bars only). Here profiling is a first-class utility: `trace()` wraps
jax.profiler (TensorBoard-viewable XLA traces incl. per-kernel timing),
`StepTimer` gives steps/sec + seq/sec with compile-step exclusion, and
`annotate` names regions inside traces.
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler trace (view with TensorBoard's profile tab)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (context manager)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Throughput meter that ignores the first (compile) step.

    >>> t = StepTimer(batch_size=256)
    >>> for batch in data:
    ...     state, m = step(state, batch)
    ...     t.tick(m["loss"])          # blocks on the step's result
    >>> t.summary()  # {'steps_per_sec': ..., 'seq_per_sec': ...}
    """

    def __init__(self, batch_size: int, skip_first: int = 1):
        self.batch_size = batch_size
        self.skip_first = skip_first
        self._count = 0
        # skip_first=0 means "time from construction".
        self._t0 = time.perf_counter() if skip_first == 0 else None

    def tick(self, result=None) -> None:
        if result is not None:
            jax.block_until_ready(result)
        self._count += 1
        if self._count == self.skip_first:
            self._t0 = time.perf_counter()

    def summary(self) -> dict:
        timed = self._count - self.skip_first
        if self._t0 is None or timed <= 0:
            return {"steps_per_sec": 0.0, "seq_per_sec": 0.0}
        dt = time.perf_counter() - self._t0
        return {
            "steps_per_sec": timed / dt,
            "seq_per_sec": timed * self.batch_size / dt,
        }
