"""Profiling / tracing hooks.

The reference has NO tracing or profiling at all (SURVEY.md §5.1 — tqdm
bars only). Here profiling is a first-class utility: `trace()` wraps
jax.profiler (TensorBoard-viewable XLA traces incl. per-kernel timing),
`StepTimer` gives steps/sec + seq/sec with compile-step exclusion, and
`annotate` names regions inside traces.

Host-side span tracing, goodput accounting, and the crash flight
recorder live in `genrec_tpu/obs` (docs/OBSERVABILITY.md); a device
profile captured here lines up with those host spans via
`SpanTracer(bridge_jax=True)` and the named_scope phase labels in
core/harness.py and ops/trie.py.
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler trace (view with TensorBoard's profile tab)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (context manager)."""
    return jax.profiler.TraceAnnotation(name)


class ProfileWindow:
    """Capture a jax.profiler trace of n_steps training steps, starting
    after ``start`` steps have completed (default 1: skip the compile
    step).

    Trainers construct one unconditionally (n_steps=0 or an empty logdir
    disables) and call ``tick(n_finished)`` after each optimizer step with
    the RUNNING COUNT of finished steps; ``close()`` stops a still-open
    trace when the run ends early.
    """

    def __init__(self, logdir: str, n_steps: int = 0, start: int = 1):
        self.logdir = logdir
        self.n_steps = n_steps
        self.start = start
        self._state = "idle" if (n_steps > 0 and logdir) else "done"

    def tick(self, n_finished: int) -> None:
        """Call after each step with the 1-based count of finished steps."""
        if self._state == "idle" and n_finished >= self.start:
            jax.profiler.start_trace(self.logdir)
            self._state = "on"
        elif self._state == "on" and n_finished >= self.start + self.n_steps:
            jax.profiler.stop_trace()
            self._state = "done"

    def close(self) -> None:
        if self._state == "on":
            jax.profiler.stop_trace()
        self._state = "done"


def perf_summary(timer: "StepTimer") -> dict:
    """StepTimer summary extended with the per-chip north-star metric
    (BASELINE.md: seq/sec/chip)."""
    s = timer.summary()
    s["seq_per_sec_per_chip"] = s["seq_per_sec"] / max(jax.device_count(), 1)
    return s


def log_epoch_perf(logger, tracker, epoch, epoch_loss, n_batches, timer,
                   tokens_per_step: float | None = None) -> float:
    """Shared epoch-end summary used by every trainer: block once on the
    chained loss scalar (closing the async-dispatch timing window), log
    loss + throughput, feed the Tracker. Returns the mean loss.

    ``tokens_per_step``: mean REAL tokens per step (packed trainers pass
    the epoch's device-accumulated count / n_batches) — adds tokens/sec
    and tokens/sec/chip to the perf metrics."""
    if epoch_loss is not None:
        jax.block_until_ready(epoch_loss)
    perf = perf_summary(timer)
    if tokens_per_step is not None:
        tps = perf["steps_per_sec"] * tokens_per_step
        perf["tokens_per_sec"] = tps
        perf["tokens_per_sec_per_chip"] = tps / max(jax.device_count(), 1)
    mean_loss = float(epoch_loss) / n_batches if n_batches else 0.0
    extra = (
        f", {perf['tokens_per_sec']:.0f} tok/s" if "tokens_per_sec" in perf else ""
    )
    logger.info(
        f"epoch {epoch} loss {mean_loss:.4f} "
        f"[{perf['seq_per_sec']:.1f} seq/s, "
        f"{perf['seq_per_sec_per_chip']:.1f} seq/s/chip{extra}]"
    )
    tracker.log({
        "epoch": epoch, "train/loss": mean_loss,
        **{f"perf/{k}": v for k, v in perf.items()},
    })
    return mean_loss


class StepTimer:
    """Throughput meter that ignores the first (compile) step.

    >>> t = StepTimer(batch_size=256)
    >>> for batch in data:
    ...     state, m = step(state, batch)
    ...     t.tick(m["loss"])          # blocks on the step's result
    >>> t.summary()  # {'steps_per_sec': ..., 'seq_per_sec': ...}
    """

    def __init__(self, batch_size: int, skip_first: int = 1):
        self.batch_size = batch_size
        self.skip_first = skip_first
        self._count = 0
        # skip_first=0 means "time from construction".
        self._t0 = time.perf_counter() if skip_first == 0 else None

    def tick(self, result=None) -> None:
        if result is not None:
            jax.block_until_ready(result)
        self._count += 1
        if self._count == self.skip_first:
            self._t0 = time.perf_counter()

    def summary(self) -> dict:
        timed = self._count - self.skip_first
        if self._t0 is None or timed <= 0:
            return {"steps_per_sec": 0.0, "seq_per_sec": 0.0}
        dt = time.perf_counter() - self._t0
        return {
            "steps_per_sec": timed / dt,
            "seq_per_sec": timed * self.batch_size / dt,
        }
