"""Fault-injection harness: kill, corrupt, and poison a live training run.

Chaos engineering for the fault-tolerance layer (core.fault_tolerance):
tests install a `ChaosPlan` and the training loops fire it at the exact
step/epoch it names —

- ``kill_at_step`` / ``kill_at_epoch``: deliver a real signal (SIGTERM by
  default, the TPU preemption signal) to this process mid-run, exactly
  like a spot reclaim. The PreemptionGuard latches it and the loop takes
  its normal checkpoint-and-exit path — the chaos test then resumes and
  asserts exact parity with an uninterrupted run.
- ``nan_at_steps``: overwrite every FLOAT array of the host batch with
  NaN before it ships to device (integer token batches pass through
  untouched), driving the jitted non-finite guard in core.harness.
- `truncate_checkpoint` / `garble_checkpoint`: damage an on-disk orbax
  step dir the way a crashed writer or a bad disk would, driving the
  integrity ladder in core.checkpoint.
- ``net_faults``: a seeded schedule of NETWORK faults (latency, drop,
  corruption, truncation, slow-loris, reset, hang) applied at the frame
  send/recv boundary of the cross-host serving tier — the injector
  itself lives in `genrec_tpu.disagg.chaosnet`, but the schedule rides
  the SAME plan schema as training chaos, so one `inject(...)` covers a
  whole chaos scenario (kill the host AND partition its wire).

The hooks are no-ops (one module attribute read) unless a plan is
installed, so they stay in the production loops permanently — the same
code path that serves traffic is the one chaos-tested.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
from typing import Iterable, Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class NetFault:
    """One scheduled network fault at the socket tier's frame boundary.

    Matched per wrapped endpoint by ``role`` (``"front"`` — the proxy's
    socket; ``"host"`` — a decode host's accepted connection; ``"*"``)
    and ``side`` (``"send"`` / ``"recv"``), armed for the half-open
    frame-index window ``[at_frame, at_frame + n_frames)`` counted
    per endpoint+side. Every probabilistic choice (``p``, corruption
    positions) draws from the plan's seeded RNG, so a fault sequence is
    bit-reproducible per ``net_seed``."""

    kind: str          # latency|drop|corrupt|truncate|slow_loris|reset|hang
    role: str = "*"    # which endpoint's socket ("front"|"host"|"*")
    side: str = "send"  # "send" | "recv"
    at_frame: int = 0  # first frame index the rule arms at
    n_frames: int = 1  # window length in frames
    delay_s: float = 0.0  # latency/hang sleep; slow-loris per-chunk delay
    p: float = 1.0     # per-frame firing probability (seeded)
    # Connection window: each wrap of a role's socket gets the next
    # ordinal (0, 1, ... per process+role, reconnects included), and
    # the fault only arms for ordinals in [at_conn, at_conn + n_conns).
    # n_conns=0 means every connection. This is how a schedule says
    # "blackhole the FIRST connection" and still lets the reconnect
    # that recovers from it come up clean — the property that makes a
    # zero-lost-requests chaos run deterministic instead of a race.
    at_conn: int = 0
    n_conns: int = 0


@dataclasses.dataclass
class ChaosPlan:
    kill_at_step: int | None = None  # global step (post-increment) to signal at
    kill_at_epoch: int | None = None  # epoch index to signal at (end of epoch)
    kill_signal: int = signal.SIGTERM
    nan_at_steps: frozenset[int] = frozenset()  # global steps to poison
    # Step at which CheckpointManager.save dies HARD (SIGKILL) after the
    # array snapshot but before the commit completes — a host vanishing
    # mid-save. Drives the coordinated-commit guarantee: the step must
    # never end up with a commit marker.
    die_in_save_at_step: int | None = None
    # Streaming-pipeline chaos (docs/training.md "Streaming training"):
    # record index at which StreamLogWriter.append dies HARD with a REAL
    # torn frame on disk (header + partial payload, fsync'd, then
    # SIGKILL) — drives torn-tail recovery against actual torn bytes.
    die_in_append_at_record: int | None = None
    # Step at which the streaming trainer dies HARD while its params
    # PUBLISH (the serving-facing checkpoint, distinct from the trainer's
    # own resume commit above) is still in flight — the published step
    # must never gain a commit marker, so the rollout guard never sees it.
    die_in_publish_at_step: int | None = None
    # Rollout-controller chaos: name a stage boundary ("canary" |
    # "promote") at which `maybe_crash` raises ChaosCrashError, killing
    # the controller's poll thread exactly where a process crash would —
    # its durable rollout state file is all a restarted controller gets.
    crash_rollout_at: str | None = None
    # Multi-host chaos: restrict every injection above to ONE simulated
    # host (jax.process_index()). None = fire on every process (the
    # single-process default, where process_index() is 0).
    only_process: int | None = None
    # Serving chaos: the scheduled network faults disagg.chaosnet
    # injects at the socket tier's frame boundary, plus the seed that
    # makes the whole sequence reproducible.
    net_faults: tuple[NetFault, ...] = ()
    net_seed: int = 0


def _this_process_targeted(plan: ChaosPlan) -> bool:
    if plan.only_process is None:
        return True
    import jax

    return jax.process_index() == plan.only_process


_ACTIVE: ChaosPlan | None = None


class inject:
    """Context manager installing a plan for the duration of a test."""

    def __init__(self, plan: ChaosPlan):
        self._plan = plan

    def __enter__(self) -> ChaosPlan:
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self._plan
        return self._plan

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = self._prev
        return False


def active() -> ChaosPlan | None:
    return _ACTIVE


def install(plan: ChaosPlan | None) -> None:
    """Process-lifetime install (no context manager to unwind): a child
    process — a spawned decode host — installs its plan once at startup
    and keeps it until exit."""
    global _ACTIVE
    _ACTIVE = plan


#: Env var carrying a net-fault schedule into a CHILD process (a
#: spawned decode host cannot enter the parent's `inject` block).
NET_PLAN_ENV = "GENREC_CHAOS_NET_PLAN"


def net_plan_to_env(plan: ChaosPlan) -> str:
    """Serialize the plan's NETWORK schedule for `NET_PLAN_ENV` (the
    process-kill/NaN fields stay parent-side — a child that should die
    gets its own plan)."""
    return json.dumps({
        "net_seed": plan.net_seed,
        "net_faults": [dataclasses.asdict(f) for f in plan.net_faults],
    })


def install_net_plan_from_env() -> ChaosPlan | None:
    """Child-process hook: install the schedule `NET_PLAN_ENV` carries
    (no-op without it). Returns the installed plan."""
    raw = os.environ.get(NET_PLAN_ENV)
    if not raw:
        return None
    spec = json.loads(raw)
    plan = ChaosPlan(
        net_seed=int(spec.get("net_seed", 0)),
        net_faults=tuple(NetFault(**f) for f in spec.get("net_faults", ())),
    )
    install(plan)
    return plan


def maybe_kill(step: int | None = None, epoch: int | None = None) -> None:
    """Fire the plan's signal when the loop reaches the named point.

    The signal goes through the real OS delivery path (os.kill to self),
    so whatever handler the trainer installed — the PreemptionGuard —
    latches it exactly as it would a fleet preemption."""
    plan = _ACTIVE
    if plan is None or not _this_process_targeted(plan):
        return
    fire = (step is not None and plan.kill_at_step == step) or (
        epoch is not None and plan.kill_at_epoch == epoch
    )
    if fire:
        # Flight-record BEFORE delivery: the handler (or default action)
        # may end the process, and a chaos post-mortem should show the
        # injection as its own event ahead of the signal receipt.
        _flight_record_and_dump(
            "chaos_kill", reason="chaos_kill",
            step=step, epoch=epoch, signum=int(plan.kill_signal),
        )
        os.kill(os.getpid(), plan.kill_signal)


def maybe_die_in_save(step: int) -> None:
    """Die HARD (SIGKILL — no handlers, no atexit, no orbax cleanup) when
    the plan names this checkpoint step, simulating a host lost mid-save.
    Called by `CheckpointManager.save` after the in-memory snapshot, while
    the directory write/commit is still in flight."""
    plan = _ACTIVE
    if plan is None or not _this_process_targeted(plan):
        return
    if plan.die_in_save_at_step == step:
        # SIGKILL runs no handlers: this dump is the ONLY post-mortem.
        _flight_record_and_dump(
            "chaos_die_in_save", reason="chaos_die_in_save", step=step,
        )
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_die_in_append(record: int, partial_write=None) -> None:
    """Die HARD (SIGKILL) when the plan names this log record, leaving a
    REAL torn tail: ``partial_write`` (the caller's torn-frame writer —
    StreamLogWriter passes one that puts the header plus half the
    payload durably on disk) runs first, then the process is killed with
    the frame incomplete. Called by `StreamLogWriter.append` BEFORE the
    full frame write."""
    plan = _ACTIVE
    if plan is None or not _this_process_targeted(plan):
        return
    if plan.die_in_append_at_record == record:
        if partial_write is not None:
            partial_write()
        _flight_record_and_dump(
            "chaos_die_in_append", reason="chaos_die_in_append",
            record=record,
        )
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_die_in_publish(step: int) -> None:
    """Die HARD (SIGKILL) when the plan names this PUBLISHED step, while
    the publish checkpoint's async write is still in flight — the
    serving-facing step must never end up committed. Called by the
    streaming trainer between starting the publish save and waiting on
    it."""
    plan = _ACTIVE
    if plan is None or not _this_process_targeted(plan):
        return
    if plan.die_in_publish_at_step == step:
        _flight_record_and_dump(
            "chaos_die_in_publish", reason="chaos_die_in_publish", step=step,
        )
        os.kill(os.getpid(), signal.SIGKILL)


class ChaosCrashError(RuntimeError):
    """Raised by `maybe_crash` to kill a component's thread in place —
    the in-process analogue of SIGKILL for components (the rollout
    controller) whose crash-consistency contract is a durable state
    file, not a checkpoint."""


def maybe_crash(stage: str) -> None:
    """Raise ChaosCrashError when the plan's ``crash_rollout_at`` names
    this stage boundary. The caller must NOT catch it — the owning
    thread dies, and recovery is exercised by constructing a fresh
    component over the same durable state."""
    plan = _ACTIVE
    if plan is None or not _this_process_targeted(plan):
        return
    if plan.crash_rollout_at == stage:
        _flight_record_and_dump(
            "chaos_crash", reason="chaos_crash", stage=stage,
        )
        raise ChaosCrashError(f"chaos crash at rollout stage {stage!r}")


def _flight_record_and_dump(kind: str, reason: str, **fields) -> None:
    try:
        from genrec_tpu.obs.flight_recorder import get_flight_recorder

        rec = get_flight_recorder()
        rec.record(kind, **fields)
        rec.dump(reason=reason)
    except Exception:
        pass  # chaos injection must fire even if the recorder cannot


def poison_batches(iterator: Iterable, start_step: int) -> Iterator:
    """Wrap a (batch, valid) iterator, NaN-ing float arrays at the plan's
    steps. ``start_step`` is the global step BEFORE the first yielded
    batch (batch i lands as global step start_step + 1 + i)."""
    for i, (batch, valid) in enumerate(iterator):
        plan = _ACTIVE
        if (
            plan is not None
            and _this_process_targeted(plan)
            and (start_step + 1 + i) in plan.nan_at_steps
        ):
            batch = {
                k: (np.full_like(v, np.nan)
                    if np.issubdtype(np.asarray(v).dtype, np.floating) else v)
                for k, v in batch.items()
            }
        yield batch, valid


# -- on-disk checkpoint damage (test fixtures) ------------------------------


def _step_files(ckpt_dir: str, step: int) -> list[str]:
    root = os.path.join(ckpt_dir, str(step))
    out = []
    for base, _, files in os.walk(root):
        out.extend(os.path.join(base, f) for f in files)
    if not out:
        raise FileNotFoundError(f"no files under checkpoint step dir {root}")
    return sorted(out)


def truncate_checkpoint(ckpt_dir: str, step: int, keep_bytes: int = 8) -> None:
    """Truncate every array file of a step — a writer killed mid-flush."""
    for f in _step_files(ckpt_dir, step):
        if os.path.basename(f).startswith("_"):
            continue  # keep metadata: truncation of DATA must be caught too
        with open(f, "r+b") as fh:
            fh.truncate(min(keep_bytes, os.path.getsize(f)))


def garble_checkpoint(ckpt_dir: str, step: int, seed: int = 0) -> None:
    """Overwrite array bytes with noise — silent media corruption."""
    rng = np.random.default_rng(seed)
    for f in _step_files(ckpt_dir, step):
        if os.path.basename(f).startswith("_"):
            continue
        size = os.path.getsize(f)
        with open(f, "r+b") as fh:
            fh.write(rng.integers(0, 256, size=max(size, 1), dtype=np.uint8).tobytes())


def drop_commit_marker(ckpt_dir: str, step: int) -> None:
    """Delete the orbax commit marker — a save interrupted mid-commit."""
    from genrec_tpu.core.checkpoint import _COMMIT_MARKER

    os.remove(os.path.join(ckpt_dir, str(step), _COMMIT_MARKER))
