"""The jitted train-step factory: one compiled function per model.

Replaces the reference's Accelerate loop body (`accelerator.accumulate` /
`backward` / `clip_grad_norm_` / `optimizer.step`, tiger_trainer.py:294-318)
with a single XLA program: microbatch `lax.scan` gradient accumulation,
global-norm clip, optax update. Mixed precision is a property of the model
(bf16 params/activations) rather than an autocast context; the loss and
grad-norm math here stays fp32.

Sharding: callers place the batch with `shard_batch` (leading dim on the
"data" axis) and params replicated; jit then compiles an SPMD program where
the gradient mean is an XLA all-reduce over ICI — the DDP equivalent with
no wrapper class.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from genrec_tpu.core.state import TrainState

# loss_fn(params, batch, rng) -> (loss, aux_metrics_dict)
LossFn = Callable[[Any, Any, jax.Array], tuple[jax.Array, dict]]


def jit_train_step(step):
    """THE production jit config for a trainer's step: the train state is
    consumed by the call (the loop rebinds it), so it is donated — an
    undonated state is a dead full-model copy held in HBM across every
    step. Every trainer AND its graftlint compile-manifest entry jit
    through this one helper, so the donation audit
    (analysis/ir.py missing_donation) audits what production compiles;
    dropping the donation here fails CI instead of silently
    double-buffering."""
    return jax.jit(step, donate_argnums=0)


def make_train_step(
    loss_fn: LossFn,
    optimizer: optax.GradientTransformation,
    accum_steps: int = 1,
    clip_norm: float | None = 1.0,
    skip_nonfinite: bool = True,
):
    """Build `step(state, batch) -> (state, metrics)`, ready to jit.

    With ``accum_steps > 1`` the batch's leading dim is split into
    ``accum_steps`` microbatches scanned sequentially — same semantics as
    `Accelerator(gradient_accumulation_steps=...)` but inside one compiled
    step, so the optimizer/clip always sees the averaged full-batch grad.

    ``skip_nonfinite`` (default on) is the jitted non-finite step guard:
    when the batch loss or the (pre-clip) gradient norm is NaN/Inf, the
    optimizer update is dropped — params, opt_state and ``state.step``
    pass through UNCHANGED (the per-step RNG still advances, so a skipped
    step perturbs nothing downstream), ``state.nonfinite_count`` counts
    the consecutive-skip streak (reset to 0 by any finite step), and the
    metrics gain ``nonfinite`` (0/1 flag) + ``nonfinite_count``. Skipping
    happens entirely on device via `jnp.where` — no host sync, no branch,
    identical numerics on the finite path. Host-side policy (dumping the
    offending batch, aborting after N consecutive skips) lives in
    `core.fault_tolerance.NonFiniteMonitor`.
    """

    def grads_of(params, batch, rng):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, rng
        )
        return loss, aux, grads

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        from genrec_tpu.core.state import fast_step_rng

        rng, step_rng = jax.random.split(state.rng)
        # TPU: dropout bits come from the hardware RngBitGenerator instead
        # of threefry (~40% of a small-model step); state.rng itself stays
        # threefry so checkpoints are backend-portable (see fast_step_rng).
        step_rng = fast_step_rng(step_rng)

        # named_scope: phase labels survive into the compiled HLO/XLA
        # profile, so a device trace (core.profiling.trace / ProfileWindow)
        # attributes kernel time to grads vs clip vs optimizer — the
        # device-side half of the obs layer's host spans.
        if accum_steps == 1:
            with jax.named_scope("grads"):
                loss, aux, grads = grads_of(state.params, batch, step_rng)
        else:
            def split_micro(x):
                return x.reshape((accum_steps, x.shape[0] // accum_steps) + x.shape[1:])

            micro = jax.tree_util.tree_map(split_micro, batch)
            keys = jax.random.split(step_rng, accum_steps)

            def body(carry, mb_and_key):
                mb, key = mb_and_key
                loss, aux, grads = grads_of(state.params, mb, key)
                acc_loss, acc_grads = carry
                acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, grads)
                return (acc_loss + loss, acc_grads), aux

            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), state.params
            )
            with jax.named_scope("grads"):
                (loss_sum, grad_sum), auxes = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), zero_grads), (micro, keys)
                )
            loss = loss_sum / accum_steps
            grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grad_sum)
            aux = jax.tree_util.tree_map(lambda a: a.mean(axis=0), auxes)

        with jax.named_scope("grad_clip"):
            if clip_norm is not None:
                gnorm = optax.global_norm(grads)
                scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            else:
                gnorm = optax.global_norm(grads)

        with jax.named_scope("optimizer_update"):
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": gnorm, **aux}
        if skip_nonfinite:
            # NaN/Inf batch: keep the old params/opt_state/step (the NaN
            # update would poison Adam's moments even at lr=0), bump the
            # consecutive-skip streak. `where` with a scalar predicate
            # selects whole buffers — on the finite path this is the
            # identity, bit-for-bit.
            with jax.named_scope("nonfinite_guard"):
                ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new, old
                )
                params = keep(params, state.params)
                opt_state = keep(opt_state, state.opt_state)
                step = state.step + jnp.where(ok, 1, 0).astype(state.step.dtype)
                nonfinite_count = jnp.where(ok, 0, state.nonfinite_count + 1).astype(
                    state.nonfinite_count.dtype
                )
                metrics["nonfinite"] = (~ok).astype(jnp.float32)
                metrics["nonfinite_count"] = nonfinite_count.astype(jnp.float32)
        else:
            step = state.step + 1
            nonfinite_count = state.nonfinite_count
        new_state = state.replace(
            step=step, params=params, opt_state=opt_state, rng=rng,
            nonfinite_count=nonfinite_count,
        )
        return new_state, metrics

    return step
