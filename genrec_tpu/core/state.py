"""Train state pytree: params + optimizer state + step + PRNG key."""

from __future__ import annotations

from typing import Any

import jax
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation, rng: jax.Array):
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            rng=rng,
        )
