"""Train state pytree: params + optimizer state + step + PRNG key."""

from __future__ import annotations

from typing import Any

import jax
import optax
from flax import struct


def fast_step_rng(rng: jax.Array) -> jax.Array:
    """Re-key a per-step RNG onto the fast generator for the backend.

    On TPU the default threefry2x32 dropout-mask generation costs ~40% of
    a small-model train step (measured on the TIGER bench config: 24.7 ->
    17.0 ms/step, +45% seq/s); 'rbg' lowers random bits to XLA's hardware
    RngBitGenerator instead (the standard TPU-training choice, cf. t5x /
    maxtext). CPU keeps threefry so virtual-mesh CI and golden tests are
    bit-stable across rounds.

    Called INSIDE the jitted step (core.harness) on the freshly-split step
    key, so the state's stored key stays threefry — checkpointed key data
    keeps its (2,) shape and resumes work across backends and across
    rounds. The full 64 bits of the threefry key seed the 128-bit rbg key
    (data duplicated, no entropy discarded); derivation is deterministic,
    so seeded runs stay reproducible per backend.
    """
    if jax.default_backend() != "tpu":
        return rng
    import jax.numpy as jnp

    data = jax.random.key_data(rng).ravel()
    return jax.random.wrap_key_data(
        jnp.concatenate([data, data]), impl="rbg"
    )


class TrainState(struct.PyTreeNode):
    """``step`` counts APPLIED optimizer updates (the non-finite guard in
    core.harness skips the update — and the step increment — on NaN/Inf
    batches, so LR schedules keyed on ``step`` never advance past skipped
    work). ``nonfinite_count`` is the running streak of CONSECUTIVE
    skipped steps; it lives in the state pytree so it is checkpointed and
    a resumed run keeps counting toward the abort threshold instead of
    resetting it."""

    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    nonfinite_count: jax.Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation, rng: jax.Array):
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            rng=rng,
            nonfinite_count=jnp.zeros((), jnp.int32),
        )
