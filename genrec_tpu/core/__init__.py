"""Training core: train state, the jitted step factory, checkpointing, logging."""

from genrec_tpu.core.state import TrainState
from genrec_tpu.core.harness import make_train_step

__all__ = ["TrainState", "make_train_step"]
