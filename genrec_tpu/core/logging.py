"""Logging + experiment tracking plumbing shared by all trainers.

Mirrors the reference's per-trainer `setup_logger` (sasrec_trainer.py:20-36)
and wandb usage (define_metric namespacing, :105-107), with wandb made
optional: if the package is missing or disabled, `Tracker` is a no-op, so
trainers never branch on availability.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Mapping

from genrec_tpu.obs.flight_recorder import json_safe


def setup_logger(save_dir: str | None = None, name: str = "genrec_tpu") -> logging.Logger:
    """Process-wide logger; safe to call once per trainer stage.

    A multi-stage pipeline calls this with a DIFFERENT save_dir per stage
    (pipelines.py runs rqvae then the generator in one process) — each new
    save_dir gets its own train.log file handler, while duplicate calls
    for an already-attached path are no-ops."""
    logger = logging.getLogger(name)
    logger.propagate = False  # avoid duplicate lines via the root logger
    fmt = logging.Formatter("%(asctime)s %(levelname)s %(message)s")
    if not logger.handlers:
        logger.setLevel(logging.INFO)
        sh = logging.StreamHandler(sys.stdout)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
    if save_dir:
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.abspath(os.path.join(save_dir, "train.log"))
        attached = {
            getattr(h, "baseFilename", None)
            for h in logger.handlers
            if isinstance(h, logging.FileHandler)
        }
        if path not in attached:
            fh = logging.FileHandler(path)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
    return logger


def log_occupancy(logger, tracker, epoch: int, real_tokens: float,
                  slot_tokens: float) -> float:
    """Per-epoch packed-batch occupancy (real tokens / padded slots), so
    padding waste is visible in wandb/stdout without a profiler.

    Called by the trainers that pack, with the epoch's device-accumulated
    real-token count and the static slot count they fed the step. Returns
    the occupancy fraction."""
    occ = float(real_tokens) / max(float(slot_tokens), 1.0)
    logger.info(
        f"epoch {epoch} batch occupancy {occ:.1%} "
        f"({int(real_tokens)} real tokens / {int(slot_tokens)} slots)"
    )
    tracker.log({
        "epoch": epoch,
        "perf/occupancy": occ,
        "perf/real_tokens": float(real_tokens),
        "perf/slot_tokens": float(slot_tokens),
    })
    return occ


def log_serving_stats(logger, tracker, stats: Mapping[str, Any]) -> None:
    """Per-interval serving health line + tracker forwarding.

    ``stats`` is a ServingEngine.stats() snapshot. One human-readable
    line (QPS + the three latency percentiles + recompile count — the
    fields an operator scans first) goes to the logger; the full flattened
    snapshot goes to the tracker under the ``serve/`` namespace so wandb /
    metrics.jsonl dashboards get every counter."""
    t = stats.get("total_ms", {})
    logger.info(
        f"serving: qps={stats.get('qps', 0):.1f} "
        f"p50={t.get('p50', 0):.1f}ms p95={t.get('p95', 0):.1f}ms "
        f"p99={t.get('p99', 0):.1f}ms completed={stats.get('completed', 0)} "
        f"rejected={stats.get('rejected', 0)} "
        f"recompilations={stats.get('recompilations', 0)} "
        f"step={stats.get('params_step')}"
    )
    # Admit/evict/OOM-deferral counters are ENGINE totals (the metrics
    # layer does not attribute them per head): one engine-level line, so
    # they can never read as belonging to whichever head's pool line they
    # used to be printed inside.
    if stats.get("kv_pool"):
        logger.info(
            f"serving paged engine totals: admits={stats.get('admits', 0)} "
            f"evictions={stats.get('evictions', 0)} "
            f"oom_deferred={stats.get('oom_deferred_admits', 0)} "
            f"decode_steps={stats.get('decode_steps', 0)}"
        )
    # Paged decode heads: one pool-pressure line per head (pages + slot
    # occupancy + churn), so an operator sees "pool-bound" vs "idle" at a
    # glance — the day-one gauges the paged KV cache ships with.
    for head, g in (stats.get("kv_pool") or {}).items():
        logger.info(
            f"serving kv-pool[{head}]: pages {g.get('pages_in_use', 0)}/"
            f"{g.get('pages_in_use', 0) + g.get('pages_free', 0)} in use, "
            f"slots {g.get('slots_active', 0)}/{g.get('slots_total', 0)}, "
            f"kv_tokens={g.get('kv_tokens_resident', 0)}"
        )
    # Cross-request prefix cache: one line per head — warm-hit rate, KV
    # tokens served without prefill, index size, and retained-page HBM —
    # so "is repeat traffic actually landing warm" reads off the same
    # interval line as the pool gauges.
    for head, g in (stats.get("prefix_cache") or {}).items():
        lookups = g.get("lookups", 0)
        hits = g.get("hits", 0)
        rate = 100.0 * hits / lookups if lookups else 0.0
        logger.info(
            f"serving prefix-cache[{head}]: {hits}/{lookups} warm hits "
            f"({rate:.1f}%), warm_tokens={g.get('warm_tokens', 0)}, "
            f"entries={g.get('entries', 0)}, retained "
            f"{g.get('retained_pages', 0)} pages "
            f"({g.get('retained_bytes', 0) / 2**20:.2f} MB), "
            f"evictions={g.get('evictions', 0)} "
            f"invalidations={g.get('invalidations', 0)}"
        )
    # Speculative tree decode: one line per spec head — codes committed
    # per target invocation (1.0 == plain decode), draft acceptance, and
    # the accept-length histogram — so "is speculation actually paying"
    # reads off the same interval line as the pool gauges.
    for head, g in (stats.get("spec") or {}).items():
        slot_steps = g.get("slot_steps", 0)
        accepted = g.get("accepted", 0)
        hist = ",".join(
            f"{k.rsplit('_', 1)[-1]}:{v}"
            for k, v in sorted((g.get("accept_len_hist") or {}).items())
        )
        logger.info(
            f"serving spec[{head}]: {g.get('codes_per_invocation', 0.0):.2f} "
            f"codes/invocation ({accepted} codes over {slot_steps} "
            f"slot-steps in {g.get('spec_steps', 0)} invocations; "
            f"{accepted - slot_steps} speculated codes accepted, "
            f"{g.get('drafted', 0)} tree tokens drafted), "
            f"accept_len[{hist}]"
        )
    # Device-memory ledger (obs/memory.py): one HBM line per head —
    # ledger total vs the declared budget with headroom %, so "how close
    # to OOM is this replica" reads off the same interval line as the
    # pool gauges.
    hbm = stats.get("hbm") or {}
    budget = hbm.get("budget_bytes")
    for head, h in (hbm.get("heads") or {}).items():
        total = h.get("total_bytes", 0)
        line = (
            f"serving hbm[{head}]: {total / 2**20:.2f} MB "
            f"(operands {h.get('operand_bytes', 0) / 2**20:.2f} MB + "
            f"transient peak {h.get('transient_peak_bytes', 0) / 2**20:.2f} MB"
            f" across {h.get('n_executables', 0)} executables)"
        )
        if budget:
            line += (
                f", budget {budget / 2**20:.1f} MB, "
                f"headroom {hbm.get('headroom_pct', 0.0):.1f}%"
            )
        logger.info(line)
    # SLO shed state: one line while any head is ACTIVELY shedding
    # (gating on the lifetime overload counter would log forever after
    # the first episode; the counter still reaches dashboards via the
    # tracker flatten below).
    slo = stats.get("slo")
    if slo:
        shed = [h for h, s in (slo.get("heads") or {}).items()
                if s.get("shedding")]
        if shed:
            logger.info(
                f"serving slo: shedding={sorted(shed)} "
                f"overload_rejected={stats.get('overload_rejected', 0)}"
            )

    def _flatten(prefix: str, tree: Mapping, out: dict) -> None:
        for k, v in tree.items():
            if isinstance(v, Mapping):
                _flatten(f"{prefix}{k}/", v, out)
            elif isinstance(v, (int, float)):
                out[f"{prefix}{k}"] = v

    flat: dict[str, Any] = {}
    _flatten("serve/", stats, flat)
    tracker.log(flat)


def log_goodput(logger, tracker, epoch: int, report: Mapping[str, Any],
                fleet: bool = False) -> None:
    """Per-epoch goodput line + tracker forwarding (obs/goodput.py).

    One operator-readable line (goodput % + the top overhead buckets) and
    the full bucket breakdown under the ``goodput/`` tracker namespace
    (``goodput/fleet/`` for the all-host aggregate)."""
    buckets = report.get("buckets", {})
    wall = max(float(report.get("wall_s", 0.0)), 1e-9)
    overheads = sorted(
        ((k, v) for k, v in buckets.items() if k != "compute" and v > 0),
        key=lambda kv: -kv[1],
    )[:3]
    detail = ", ".join(f"{k} {100 * v / wall:.1f}%" for k, v in overheads)
    scope = "fleet goodput" if fleet else "goodput"
    logger.info(
        f"epoch {epoch} {scope} {report.get('goodput_pct', 0.0):.1f}% "
        f"of {wall:.1f}s wall" + (f" [{detail}]" if detail else "")
    )
    ns = "goodput/fleet" if fleet else "goodput"
    payload = {
        "epoch": epoch,
        f"{ns}/pct": float(report.get("goodput_pct", 0.0)),
        f"{ns}/wall_s": wall,
        **{f"{ns}/{k}_s": float(v) for k, v in buckets.items()},
    }
    # Peak device bytes (obs.memory.device_memory_stats, folded in by
    # the packed loop on backends whose allocator exposes it).
    if report.get("peak_device_bytes"):
        payload[f"{ns}/peak_device_bytes"] = float(report["peak_device_bytes"])
    tracker.log(payload)


class Tracker:
    """wandb-compatible metric tracker with a JSONL fallback.

    Always writes metrics to ``<save_dir>/metrics.jsonl`` (greppable,
    survives without any service); additionally forwards to wandb when
    enabled and importable.
    """

    def __init__(
        self,
        enabled: bool = False,
        project: str = "genrec_tpu",
        config: Mapping[str, Any] | None = None,
        save_dir: str | None = None,
    ):
        self._wandb = None
        self._file = None
        if save_dir:
            os.makedirs(save_dir, exist_ok=True)
            self._file = open(os.path.join(save_dir, "metrics.jsonl"), "a")
        if enabled:
            try:
                import wandb

                wandb.init(project=project, config=dict(config or {}))
                wandb.define_metric("train/*", step_metric="global_step")
                wandb.define_metric("eval/*", step_metric="epoch")
                self._wandb = wandb
            except Exception:
                self._wandb = None

    def log(self, metrics: Mapping[str, Any]) -> None:
        payload = {k: (float(v) if hasattr(v, "__float__") else v) for k, v in metrics.items()}
        if self._file:
            # json.dumps writes bare NaN/Infinity tokens for non-finite
            # floats — NOT valid JSON, so one diverging loss would make
            # metrics.jsonl unreadable to any strict parser. Serialize
            # them as null (json_safe, shared with the flight recorder;
            # fallback_repr=False keeps dumps raising on genuinely
            # unserializable values); allow_nan=False is the backstop
            # that keeps this a hard guarantee rather than a best effort.
            line = json_safe({"t": time.time(), **payload}, fallback_repr=False)
            self._file.write(json.dumps(line, allow_nan=False) + "\n")
            self._file.flush()
        if self._wandb:
            self._wandb.log(payload)

    def finish(self) -> None:
        if self._file:
            self._file.close()
            self._file = None
        if self._wandb:
            self._wandb.finish()
