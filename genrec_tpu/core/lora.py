"""LoRA via param surgery (reference wraps peft, lcrec_trainer.py:306-315).

Pure-pytree implementation: `lora_init` creates low-rank (A, B) factors
for every Dense kernel whose path matches a target substring;
`lora_merge` produces effective params W + (alpha/r) * A @ B. Training
optimizes ONLY the LoRA tree (the base stays frozen and closed over), so
optimizer state is tiny — the standard LoRA memory win.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


def lora_init(
    params: Any,
    key: jax.Array,
    rank: int = 8,
    targets: Sequence[str] = ("q_proj", "v_proj"),
) -> dict:
    """Return {path_str: {"a": (in, r), "b": (r, out)}} for matching 2D kernels.

    A ~ N(0, 1/r), B = 0 — so the merged model starts exactly at the base.
    """
    flat = {}

    def visit(path, leaf):
        p = _path_str(path)
        if (
            leaf.ndim == 2
            and p.endswith("kernel")
            and any(t in p for t in targets)
        ):
            nonlocal key
            key, sub = jax.random.split(key)
            d_in, d_out = leaf.shape
            flat[p] = {
                "a": jax.random.normal(sub, (d_in, rank), leaf.dtype) / rank,
                "b": jnp.zeros((rank, d_out), leaf.dtype),
            }
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    return flat


def lora_merge(params: Any, lora: dict, alpha: float = 16.0, rank: int = 8) -> Any:
    """Effective params: W + (alpha/rank) * A @ B at matched paths."""
    scale = alpha / rank

    def visit(path, leaf):
        p = _path_str(path)
        if p in lora:
            return leaf + scale * (lora[p]["a"] @ lora[p]["b"])
        return leaf

    return jax.tree_util.tree_map_with_path(visit, params)


def lora_param_count(lora: dict) -> int:
    return sum(
        int(v["a"].size + v["b"].size) for v in lora.values()
    )
