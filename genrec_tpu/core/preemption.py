"""Preemption-safe training: catch SIGTERM/SIGINT, checkpoint, exit clean.

The reference has no failure/preemption handling at all (SURVEY.md §5.3:
no torchelastic, no heartbeat; recovery = manual restart from the last
periodic checkpoint, losing everything since). TPU fleets preempt:
maintenance events and spot reclaims deliver SIGTERM with a grace
window. This guard turns that signal into a final checkpoint + clean
exit, so `resume_from_checkpoint` continues from the preempted step
instead of the last periodic save.

Usage (every trainer):

    guard = PreemptionGuard(logger)
    for epoch ...:
        for batch ...:
            ...
        if guard.fired:
            ckpt.save(epoch, state)   # durable: manager save + wait
            return ...                # clean exit -> scheduler restarts

The flag is checked at epoch granularity by default because steps are
milliseconds and the grace window is tens of seconds; `check_every`
tighter loops can poll `guard.fired` per step.
"""

from __future__ import annotations

import signal
import threading


class PreemptionGuard:
    """Latches the first SIGTERM/SIGINT; restores prior handlers on close.

    Installs only in the main thread (signal.signal raises elsewhere —
    e.g. when a trainer runs inside a test worker thread); off the main
    thread the guard is inert and `fired` stays False.
    """

    def __init__(self, logger=None, signals=(signal.SIGTERM,)):
        self._fired = threading.Event()
        self._logger = logger
        self._prev = {}
        if threading.current_thread() is threading.main_thread():
            for sig in signals:
                self._prev[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame):
        if self._logger is not None:
            self._logger.warning(
                f"signal {signal.Signals(signum).name}: finishing the "
                "current epoch, checkpointing, then exiting cleanly"
            )
        self._fired.set()

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def close(self) -> None:
        """Restore the previous handlers (tests / nested trainers)."""
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev = {}
