"""Preemption-safe training: catch SIGTERM/SIGINT, checkpoint, exit clean.

The reference has no failure/preemption handling at all (SURVEY.md §5.3:
no torchelastic, no heartbeat; recovery = manual restart from the last
periodic checkpoint, losing everything since). TPU fleets preempt:
maintenance events and spot reclaims deliver SIGTERM with a grace
window. This guard turns that signal into a final checkpoint + clean
exit, so `resume_from_checkpoint` continues from the preempted step
instead of the last periodic save.

Usage (every trainer — STEP granularity via trainers.packed_loop):

    guard = PreemptionGuard(logger)
    loop = PackedTrainLoop(..., guard=guard, ckpt=ckpt)
    # run_epoch polls the guard after every optimizer step (fleet-wide
    # OR on multi-host, loop.fleet_preempted); on fire it writes a
    # step-granular resume point (TrainState + data-iterator cursor,
    # core.fault_tolerance.save_resume_point) and returns
    # preempted=True — resume continues at the exact next batch. Do NOT
    # hand-roll an epoch-granular `if guard.fired: save(epoch - 1)`
    # loop: a signal during the final epoch would save nothing (the
    # hole PR 4 closed for cobra/lcrec).

Polling `fired` is a lock-free Event read — cheap enough for per-step
checks even at millisecond step times.
"""

from __future__ import annotations

import signal
import threading


class PreemptionGuard:
    """Latches the first SIGTERM/SIGINT; restores prior handlers on close.

    Both signals latch by default: TPU fleets deliver SIGTERM for
    maintenance/spot reclaims, and an operator ^C (SIGINT) deserves the
    same checkpoint-then-exit instead of a stack trace mid-write.

    The latch is ONE-SHOT: the first signal restores the previous
    handlers immediately, so a second ^C / SIGTERM falls through to them
    (default: KeyboardInterrupt / terminate) — a run hung between poll
    points, or a guard left installed by an aborted run, can always be
    escalated without SIGKILL. Orbax commits are atomic (tmp + rename),
    so an escalated kill mid-save never leaves a committed corrupt step.

    Installs only in the main thread (signal.signal raises elsewhere —
    e.g. when a trainer runs inside a test worker thread); off the main
    thread the guard is inert and `fired` stays False.
    """

    def __init__(self, logger=None, signals=(signal.SIGTERM, signal.SIGINT)):
        self._fired = threading.Event()
        self._logger = logger
        self._prev = {}
        if threading.current_thread() is threading.main_thread():
            for sig in signals:
                self._prev[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame):
        if self._logger is not None:
            self._logger.warning(
                f"signal {signal.Signals(signum).name}: checkpointing at "
                "the next poll point, then exiting cleanly (send again to "
                "force the previous handler)"
            )
        self._fired.set()
        self.close()  # one-shot: next signal falls through
        # Flight recorder: the signal is the event every post-mortem
        # starts from, so record + dump NOW — the grace window may not
        # reach another dump point. Python runs handlers in the main
        # bytecode loop, so the file write here is ordinary code.
        try:
            from genrec_tpu.obs.flight_recorder import get_flight_recorder

            rec = get_flight_recorder()
            name = signal.Signals(signum).name
            rec.record("signal", signum=int(signum), name=name)
            rec.dump(reason=f"signal:{name}")
        except Exception:
            pass  # the latch must survive any recorder failure

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def close(self) -> None:
        """Restore the previous handlers (tests / nested trainers)."""
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev = {}
