"""Checkpointing via orbax: ONE format for every model.

Replaces the reference's three coexisting ad-hoc formats (torch.save dicts,
bare state_dicts, HF save_pretrained dirs — SURVEY.md §5.4) with orbax
PyTree checkpoints. Semantic-id artifacts (the RQ-VAE -> downstream-dataset
interface, amazon.py:296-313) are a separate portable .npz — see
genrec_tpu.data.sem_ids.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import shutil
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from genrec_tpu.core import chaos

logger = logging.getLogger("genrec_tpu")


def _flight():
    """Process flight recorder (obs layer): checkpoint saves, ladder
    verdicts and quarantines are exactly the events a post-mortem needs
    in order."""
    from genrec_tpu.obs.flight_recorder import get_flight_recorder

    return get_flight_recorder()


def _per_host_type_handler_registry():
    """Type-handler registry for `CheckpointManager(per_host=True)`:
    the stock numpy/scalar handlers minus their hard-coded
    ``process_index() == 0`` write gate (orbax assumes one shared
    directory; with per-host record trees EVERY process is the sole
    writer of its own tree, in a singleton orbax process group).

    Built lazily because the ungated subclasses override PRIVATE orbax
    internals (`_background_serialize`) verified against orbax 0.7 —
    only this optional per-host mode depends on them, so an orbax that
    reorganized those internals fails HERE with an actionable error,
    not at import time for every shared-directory user."""
    from orbax.checkpoint import type_handlers as _oth

    try:

        class _AllHostsNumpyHandler(_oth.NumpyHandler):
            async def _background_serialize(self, values, infos, args=None):
                write_coros = []
                for value, info, arg in zip(values, infos, args):
                    tspec = self._get_json_tspec_write(
                        info,
                        value,
                        use_ocdbt=info.is_ocdbt_checkpoint,
                        process_index=_oth.get_process_index_for_subdir(
                            use_ocdbt=info.is_ocdbt_checkpoint,
                            override_ocdbt_process_id=(
                                self._override_ocdbt_process_id
                            ),
                        ),
                        arg=arg,
                    )
                    write_coros.append(
                        self._open_and_write(value, tspec, info.ts_context)
                    )
                await asyncio.gather(*write_coros)

        class _AllHostsScalarHandler(_oth.ScalarHandler, _AllHostsNumpyHandler):
            pass

        return _oth.create_type_handler_registry(
            (int, _AllHostsScalarHandler()),
            (float, _AllHostsScalarHandler()),
            (np.number, _AllHostsScalarHandler()),
            (np.ndarray, _AllHostsNumpyHandler()),
        )
    except AttributeError as e:
        raise RuntimeError(
            "CheckpointManager(per_host=True) needs the orbax-checkpoint "
            "0.7 type_handlers internals its ungated write handlers "
            f"subclass, but this orbax does not provide them ({e}). "
            "Install orbax-checkpoint==0.7.* or use the default "
            "shared-directory mode."
        ) from e


class CheckpointCorruptError(RuntimeError):
    """A checkpoint step failed integrity validation (missing commit
    marker, unreadable/garbled arrays, or non-finite leaves)."""


class CheckpointMismatchError(RuntimeError):
    """A checkpoint step is READABLE but its tree structure does not
    match the live state — e.g. a record written by an older code
    version. The ladder skips these (they are not damaged; a rollback
    could still use them) instead of quarantining."""


def _abs(path: str) -> str:
    return os.path.abspath(path)


def _is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def to_savable(tree: Any) -> Any:
    """Checkpoint-ready copy of a pytree.

    Typed PRNG keys become their uint32 data. Fully-addressable arrays are
    materialized as host numpy; arrays sharded across NON-addressable
    devices (multi-host tensor parallelism) are passed through as
    jax.Arrays — orbax writes distributed arrays natively, where
    np.asarray would raise. Restore goes through the trainer's
    place_state, which re-applies the target sharding.
    """

    def conv(x):
        if _is_prng_key(x):
            x = jax.random.key_data(x)
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x
        return np.asarray(x)

    return jax.tree_util.tree_map(conv, tree)


def from_savable(saved: Any, like: Any) -> Any:
    """Re-wrap leaves that were PRNG keys in ``like``, preserving the
    like-key's generator (TPU states carry 'rbg' step keys — see
    core.state.fast_step_rng — whose key data is wider than threefry's)."""

    def conv(s, l):
        if _is_prng_key(l):
            return jax.random.wrap_key_data(
                jnp.asarray(s), impl=jax.random.key_impl(l)
            )
        return s

    return jax.tree_util.tree_map(conv, saved, like)


# Shared async checkpointer: StandardCheckpointer subclasses
# AsyncCheckpointer, so save() returns once arrays are snapshotted to host
# and the directory write proceeds on a background thread (a new save
# first waits for the previous one). SURVEY.md §5.3: async checkpointing
# is the explicit exceeds-parity goal here.
_ASYNC_CKPTR: ocp.StandardCheckpointer | None = None


def _async_ckptr() -> ocp.StandardCheckpointer:
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.StandardCheckpointer()
    return _ASYNC_CKPTR


def wait_for_saves() -> None:
    """Block until every async `save_params(..., wait=False)` has landed."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_params(path: str, params: Any, wait: bool = True) -> None:
    """Save a params pytree. ``wait=False`` returns as soon as the arrays
    are snapshotted (training continues while the write is in flight);
    call `wait_for_saves()` (or save again, or read back) to join."""
    ckptr = _async_ckptr()
    ckptr.save(_abs(path), to_savable(params), force=True)
    if wait:
        ckptr.wait_until_finished()


def load_params(path: str, like: Any | None = None) -> Any:
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        restored = ckptr.restore(_abs(path), to_savable(like))
        return from_savable(restored, like)
    return ckptr.restore(_abs(path))


def _refuse_resume_below_stale_steps(
    ckpt: "CheckpointManager", resumed_step: int | None
) -> None:
    """Readable foreign records retained ABOVE the restore point (or with
    nothing restorable at all) are a trap: orbax silently refuses saves
    at steps <= the stale latest (`should_save`), so the run would
    checkpoint NOTHING while logging success — every relaunch restores
    the same old step and the work loops forever. Fail loudly instead.

    Foreign records BELOW the restore point are harmless (future saves
    key above them) and stay on disk for rollbacks."""
    stale = [
        s for s in ckpt.all_steps()
        if resumed_step is None or s > resumed_step
    ]
    if stale:
        at = (
            "start fresh on top of them"
            if resumed_step is None
            else f"resume below them (at step {resumed_step})"
        )
        raise RuntimeError(stale_refusal_message(
            ckpt.directory,
            f"steps {stale}: written by a different code version or trainer",
            at,
        ))


def stale_refusal_message(directory: str, what: str, at: str) -> str:
    """The one stale-record refusal narrative, shared by the single-host
    refusal above and the collective multi-host refusal in
    `core.fault_tolerance.resume_exact` — remediation guidance edited in
    only one copy would drift."""
    return (
        f"checkpoint directory {directory} holds records this run "
        f"cannot resume ({what}). Refusing to {at} — orbax would silently "
        "drop every save keyed below the stale latest step. Move or "
        "delete those step dirs (the records are intact; pre-PR4 "
        "epoch-keyed records can still be restored from a script via "
        "genrec_tpu.core.checkpoint.maybe_resume) and relaunch."
    )


def maybe_resume(ckpt: "CheckpointManager | None", state, replicate_fn=None):
    """LEGACY epoch-keyed resume. No trainer uses this anymore — every
    trainer resumes step-exactly through `core.fault_tolerance.resume_exact`
    (scripts/ci_checks.sh enforces the no-import rule). Kept as a
    library-level migration helper for pre-PR4 epoch-keyed checkpoints
    (bare TrainState records): call it from a script to pull the state
    out of an old directory — the trainers themselves refuse such
    directories loudly (see `_refuse_resume_below_stale_steps`).

    Checkpoints are keyed by EPOCH. Returns
    ``(state, start_epoch, global_step)`` — fresh-start values when there
    is nothing (valid) to restore. ``replicate_fn`` re-places the
    restored host arrays on the mesh.

    Restores go through the integrity ladder
    (`CheckpointManager.restore_latest_valid`): a truncated/garbled
    latest step is quarantined with a warning and the previous retained
    step is used instead of crashing the resume.
    """
    if ckpt is None or ckpt.latest_step() is None:
        return state, 0, 0
    restored, step = ckpt.restore_latest_valid(state)
    _refuse_resume_below_stale_steps(ckpt, step)
    if restored is None:
        return state, 0, 0
    if replicate_fn is not None:
        restored = replicate_fn(restored)
    start_epoch = step + 1
    return restored, start_epoch, int(restored.step)


class BestTracker:
    """Best-metric model snapshotting that SURVIVES resume.

    The best params are written to ``<dir>/best_model`` the moment a new
    best appears (not only at exit), with the metric value in a sidecar
    json — so an interrupted run never loses an earlier, better model and
    a resumed run competes against the true best-so-far.
    """

    def __init__(self, save_dir: str | None, metric: str = "Recall@10"):
        self.dir = os.path.join(save_dir, "best_model") if save_dir else None
        self.meta = self.dir + ".json" if self.dir else None
        self.metric = metric
        self.value = -1.0
        if self.meta and os.path.exists(self.meta):
            try:
                with open(self.meta) as f:
                    self.value = float(json.load(f)["value"])
            except (ValueError, KeyError, TypeError, OSError) as e:
                # A sidecar truncated by a crash mid-write (pre-atomic
                # format) must not break resume: forget the best-so-far
                # value — the next improvement re-saves model + sidecar —
                # instead of crashing every future run.
                logger.warning(
                    f"corrupt best-model sidecar {self.meta} ({e}): "
                    "resetting best-metric tracking"
                )
                self.value = -1.0

    def update(self, value: float, params) -> bool:
        if value <= self.value:
            return False
        self.value = value
        if self.dir:
            # Synchronous on purpose: the sidecar must only ever describe
            # a DURABLE best_model dir. An async write here would let a
            # crash leave value=X on disk with no params — a resumed run
            # would then never re-save anything below X and the best model
            # is lost for good. Best-improvements are rare; the epoch-level
            # CheckpointManager saves are the async path.
            save_params(self.dir, params)
            if jax.process_index() == 0:
                # Process-0-only: on a shared filesystem every host sees
                # the same best_model dir; concurrent sidecar writers
                # would race each other's tmp/replace. The orbax save
                # above is still collective (all hosts contribute
                # shards); only the tiny json is single-writer.
                # Atomic replace: a crash mid-write must never leave a
                # truncated json that breaks the next resume's float(...).
                tmp = self.meta + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"metric": self.metric, "value": value}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.meta)
        return True

    def best_params(self, like):
        """Best params seen across ALL runs (disk), or None if none saved."""
        wait_for_saves()
        if self.dir and os.path.exists(self.dir) and self.value > -1.0:
            return load_params(self.dir, like=like)
        return None


# Orbax finalizes a step by renaming its tmp dir and then writing this
# marker (orbax 0.5+). A step dir without it was interrupted mid-commit.
_COMMIT_MARKER = "_CHECKPOINT_METADATA"


class CheckpointManager:
    """Step-numbered training checkpoints with auto-resume.

    Covers (and exceeds — the reference has no auto-resume discovery) the
    `resume_from_checkpoint` flow of tiger_trainer.py:248-256. Restores
    can run through an INTEGRITY LADDER (`restore_latest_valid`): newest
    retained step first, validated as (1) orbax commit marker present,
    (2) arrays readable + tree structure matches the live state, (3) every
    float leaf finite — a step failing any rung is quarantined to
    ``<dir>/quarantine/p<process>/`` (kept for post-mortem, excluded from
    discovery) and the ladder falls through to the previous retained step.

    Multi-host semantics:

    - **Coordinated commit** (shared directory, the default): orbax
      writes every host's shards into the step's tmp dir and process 0
      finalizes (rename + commit marker) only after an ALL-HOST barrier
      through the distributed coordination service — a host dying
      mid-save can never yield a step that is commit-markered for some
      hosts and absent for others. The barrier is bounded by
      ``commit_timeout_secs`` so a lost host surfaces as an error on the
      survivors instead of a silent hang.
    - **Per-host directories** (``per_host=True``): each process keeps an
      independent record tree under ``<dir>/p<process>/`` with no
      cross-host coordination — the layout for host-local disks. The
      orbax manager runs in a SINGLETON process group (``primary_host``
      = this process, ``active_processes`` = {this process}) so every
      host writes, finalizes, and commit-markers its own tree; trees
      must be host-local (numpy leaves — cross-process jax.Arrays need
      the shared-directory mode). Restores then MUST go through
      `restore_latest_valid_consensus`, which makes every host restore
      the SAME step (or aborts loudly with a per-host validity report).
    """

    def __init__(self, directory: str, max_to_keep: int = 3, *,
                 per_host: bool = False, commit_timeout_secs: int = 300):
        self.per_host = bool(per_host and jax.process_count() > 1)
        root = _abs(directory)
        async_options = ocp.options.AsyncOptions(
            timeout_secs=commit_timeout_secs
        )
        if self.per_host:
            pid = jax.process_index()
            root = os.path.join(root, f"p{pid}")
            # Singleton process group: orbax's own barriers and primary-
            # host gating collapse to this process alone. The write gate
            # baked into the stock numpy type handler still points at
            # global process 0, so per-host trees use the ungated
            # handlers above (and plain zarr, not OCDBT — the per-process
            # OCDBT merge machinery serves the shared-directory layout).
            mp_options = ocp.options.MultiprocessingOptions(
                primary_host=pid,
                active_processes={pid},
                barrier_sync_key_prefix=f"perhost{pid}",
            )
            registry = _per_host_type_handler_registry()
            os.makedirs(root, exist_ok=True)  # orbax create=False needs it
            self.directory = root
            self._mgr = ocp.CheckpointManager(
                root,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep,
                    create=False,
                    async_options=async_options,
                    multiprocessing_options=mp_options,
                ),
                item_handlers=ocp.PyTreeCheckpointHandler(
                    use_ocdbt=False,
                    multiprocessing_options=mp_options,
                    type_handler_registry=registry,
                ),
            )
            return
        self.directory = root
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                async_options=async_options,
            ),
        )

    def _save_args(self, tree: Any):
        # Per-host managers carry an explicit handler (PyTree args);
        # shared-directory managers use the standard route.
        if self.per_host:
            return ocp.args.PyTreeSave(tree)
        return ocp.args.StandardSave(tree)

    def save(self, step: int, state: Any) -> None:
        _flight().record("checkpoint_save", step=step,
                         directory=self.directory)
        saved = self._mgr.save(step, args=self._save_args(to_savable(state)))
        # Chaos hook: a host lost MID-SAVE (SIGKILL with the directory
        # write still in flight on the background thread). The
        # coordinated-commit guarantee under test: the marker is written
        # by process 0 only after the all-host barrier, so this step must
        # never become restorable anywhere.
        chaos.maybe_die_in_save(step)
        # orbax's should_save REFUSES saves keyed <= the retained latest
        # step, returning False with no error. Re-saving the exact latest
        # key is benign (identical record, e.g. a preemption landing on a
        # just-written epoch boundary); anything else silently dropping a
        # checkpoint is the worst failure mode this layer exists to
        # prevent — surface it.
        if not saved and step != self._mgr.latest_step():
            raise RuntimeError(
                f"orbax refused to save checkpoint step {step} (latest "
                f"retained step is {self._mgr.latest_step()}): stale "
                "higher-numbered records in the directory? The save did "
                "NOT happen."
            )

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def wait(self) -> None:
        """Join any in-flight async save (durability barrier)."""
        self._mgr.wait_until_finished()

    def reload(self) -> None:
        """Re-read the step listing from disk. Needed when another host
        sharing the directory may have quarantined steps since this
        manager last scanned (the consensus pass does)."""
        self._mgr.reload()

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        like = to_savable(state_like)
        restored = self._mgr.restore(
            step,
            args=(
                ocp.args.PyTreeRestore(like)
                if self.per_host
                else ocp.args.StandardRestore(like)
            ),
        )
        return from_savable(restored, state_like)

    # -- integrity ladder ---------------------------------------------------

    def validate_and_restore(self, state_like: Any, step: int) -> Any:
        """One ladder rung: restore ``step`` or raise
        CheckpointCorruptError (damaged) / CheckpointMismatchError
        (readable but structurally foreign, e.g. written pre-upgrade).

        The finite-leaves rung scans every float leaf once on host —
        O(checkpoint size) reads, which the restore already paid for.
        """
        marker = os.path.join(self.directory, str(step), _COMMIT_MARKER)
        if not os.path.exists(marker):
            raise CheckpointCorruptError(
                f"step {step}: missing orbax commit marker {_COMMIT_MARKER} "
                "(interrupted mid-commit?)"
            )
        try:
            # Raises on unreadable/truncated arrays and on any mismatch
            # between the stored tree and the live state's structure.
            restored = self.restore(state_like, step)
        except Exception as e:
            # Disambiguate "damaged bytes" from "different layout": a
            # METADATA read (tree structure only, no array bytes — cheap
            # even for multi-GB records) succeeding means the record is
            # intact, just not ours to restore (old format / other
            # trainer). Quarantining it would destroy a checkpoint a
            # rollback could still use.
            ckptr = ocp.StandardCheckpointer()
            try:
                ckptr.metadata(os.path.join(self.directory, str(step), "default"))
            except Exception:
                raise CheckpointCorruptError(
                    f"step {step}: unreadable ({e})"
                ) from e
            finally:
                ckptr.close()
            raise CheckpointMismatchError(
                f"step {step}: readable but tree structure does not match "
                f"the live state ({e})"
            ) from e
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            to_savable(restored)
        ):
            arr = np.asarray(leaf)
            # jnp.issubdtype also covers the ml_dtypes floats (bf16 params)
            # that numpy's own hierarchy does not classify as floating.
            if jnp.issubdtype(arr.dtype, jnp.floating) and not np.all(
                np.isfinite(arr)
            ):
                raise CheckpointCorruptError(
                    f"step {step}: non-finite leaf "
                    f"{jax.tree_util.keystr(path)}"
                )
        return restored

    def quarantine(self, step: int) -> None:
        """Move a corrupt step dir out of discovery, keeping it on disk.

        The destination embeds ``jax.process_index()``: on a shared
        filesystem every host runs the ladder over the same files, so
        concurrent quarantines would otherwise clobber each other's
        post-mortem artifacts. The losing host of a move race finds the
        source already gone — which is fine, the step is out of
        discovery either way."""
        _flight().record("checkpoint_quarantine", step=step,
                         directory=self.directory)
        src = os.path.join(self.directory, str(step))
        qdir = os.path.join(
            self.directory, "quarantine", f"p{jax.process_index()}"
        )
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, str(step))
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qdir, f"{step}.{n}")
        try:
            if os.path.exists(src):
                shutil.move(src, dst)
        except (FileNotFoundError, shutil.Error) as e:
            logger.warning(
                f"quarantine of step {step} lost a move race ({e}): "
                "another host already moved it"
            )
        self._mgr.reload()  # drop the manager's cached step listing

    def restore_latest_valid(
        self, state_like: Any, extra_validate=None
    ) -> tuple[Any, int] | tuple[None, None]:
        """Walk retained steps newest-first; quarantine every CORRUPT one
        (structure mismatches are skipped in place — see
        CheckpointMismatchError); return ``(restored, step)`` for the
        first valid, or (None, None) when nothing survives.

        ``extra_validate(restored, step)`` lets the caller add a rung
        (e.g. the resume-point format tag) — raise
        CheckpointMismatchError from it to skip that step in place and
        keep walking."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            try:
                restored = self.validate_and_restore(state_like, step)
                if extra_validate is not None:
                    extra_validate(restored, step)
                _flight().record("integrity_ladder", step=step,
                                 verdict="valid")
                return restored, step
            except CheckpointCorruptError as e:
                logger.warning(
                    f"checkpoint integrity: {e} — quarantining and falling "
                    "back to the previous retained step"
                )
                _flight().record("integrity_ladder", step=step,
                                 verdict="corrupt", error=str(e)[:500])
                self.quarantine(step)
            except CheckpointMismatchError as e:
                logger.warning(
                    f"checkpoint integrity: {e} — leaving it on disk and "
                    "falling back to the previous retained step"
                )
                _flight().record("integrity_ladder", step=step,
                                 verdict="mismatch", error=str(e)[:500])
        _flight().record("integrity_ladder", step=None, verdict="nothing_valid")
        return None, None

    def restore_latest_valid_consensus(
        self, state_like: Any, extra_validate=None
    ) -> tuple[Any, int] | tuple[None, None]:
        """Multi-host-safe `restore_latest_valid`: every host restores
        the SAME step, or the job aborts loudly.

        Each host first runs the integrity ladder locally (quarantining
        its corrupt steps), then the fleet agrees through the
        distributed runtime:

        1. allgather each host's newest-valid step (-1 = nothing valid);
        2. all equal -> done (the common case; covers all--1 = every
           host starts fresh, which is consistent);
        3. some hosts valid, some with nothing -> abort with a per-host
           validity report (silently forking restored-vs-fresh training
           state is exactly the failure this exists to prevent);
        4. disagreeing steps -> every host re-validates the fleet MIN
           (hosts whose local newest is newer fall back; a checkpoint
           truncated on one host can only pull the fleet DOWN to a step
           everyone holds), a second allgather confirms all hosts hold
           it, and any failure aborts with the report.

        A final `barrier` pins the agreement before training resumes.
        Single-process: identical to `restore_latest_valid`.
        """
        restored, step = self.restore_latest_valid(state_like, extra_validate)
        if jax.process_count() == 1:
            return restored, step
        from genrec_tpu.parallel.mesh import allgather_host_ints, barrier

        steps = allgather_host_ints([-1 if step is None else step])[:, 0]
        report = ", ".join(
            f"p{i}={'none' if s < 0 else int(s)}" for i, s in enumerate(steps)
        )
        if (steps < 0).all():
            barrier("ckpt-consensus-fresh")
            return None, None
        if (steps < 0).any():
            raise RuntimeError(
                "checkpoint consensus: some hosts have NO valid checkpoint "
                f"while others do (newest-valid per host: {report}). "
                "Restoring would fork the replicated training state; "
                "restore or clear the affected hosts' checkpoint "
                "directories and relaunch."
            )
        target = int(steps.min())
        ok = 1
        if step != target:
            logger.warning(
                f"checkpoint consensus: local newest-valid step {step} != "
                f"fleet minimum {target} (per host: {report}) — falling "
                f"back to step {target}"
            )
            try:
                restored = self.validate_and_restore(state_like, target)
                if extra_validate is not None:
                    extra_validate(restored, target)
                step = target
                # Steps above the fleet-agreed restore are VALID locally
                # but abandoned by the consensus decision: retained, orbax
                # would silently drop every future save keyed below them,
                # and the stale-step refusal would abort only THIS host
                # while its peers enter training. Quarantine them like
                # corrupt steps — on disk for rollback, out of discovery.
                for s in [s for s in self.all_steps() if s > target]:
                    logger.warning(
                        f"checkpoint consensus: quarantining locally-valid "
                        f"step {s} abandoned by the fleet-agreed restore at "
                        f"step {target}"
                    )
                    self.quarantine(s)
            except (CheckpointCorruptError, CheckpointMismatchError) as e:
                logger.error(
                    f"checkpoint consensus: cannot restore fleet-agreed "
                    f"step {target} locally: {e}"
                )
                ok = 0
        all_ok = allgather_host_ints([ok])[:, 0]
        if not (all_ok > 0).all():
            failed = [f"p{i}" for i, o in enumerate(all_ok) if not o]
            raise RuntimeError(
                f"checkpoint consensus: hosts {failed} cannot restore the "
                f"fleet-agreed step {target} (newest-valid per host: "
                f"{report}). No step is valid on every host — refusing a "
                "forked restore; inspect the per-host quarantine dirs."
            )
        barrier("ckpt-consensus")
        return restored, step

    def close(self) -> None:
        self._mgr.close()
