"""Checkpointing via orbax: ONE format for every model.

Replaces the reference's three coexisting ad-hoc formats (torch.save dicts,
bare state_dicts, HF save_pretrained dirs — SURVEY.md §5.4) with orbax
PyTree checkpoints. Semantic-id artifacts (the RQ-VAE -> downstream-dataset
interface, amazon.py:296-313) are a separate portable .npz — see
genrec_tpu.data.sem_ids.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp


def _abs(path: str) -> str:
    return os.path.abspath(path)


def _is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def to_savable(tree: Any) -> Any:
    """Checkpoint-ready copy of a pytree.

    Typed PRNG keys become their uint32 data. Fully-addressable arrays are
    materialized as host numpy; arrays sharded across NON-addressable
    devices (multi-host tensor parallelism) are passed through as
    jax.Arrays — orbax writes distributed arrays natively, where
    np.asarray would raise. Restore goes through the trainer's
    place_state, which re-applies the target sharding.
    """

    def conv(x):
        if _is_prng_key(x):
            x = jax.random.key_data(x)
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x
        return np.asarray(x)

    return jax.tree_util.tree_map(conv, tree)


def from_savable(saved: Any, like: Any) -> Any:
    """Re-wrap leaves that were PRNG keys in ``like``, preserving the
    like-key's generator (TPU states carry 'rbg' step keys — see
    core.state.fast_step_rng — whose key data is wider than threefry's)."""

    def conv(s, l):
        if _is_prng_key(l):
            return jax.random.wrap_key_data(
                jnp.asarray(s), impl=jax.random.key_impl(l)
            )
        return s

    return jax.tree_util.tree_map(conv, saved, like)


# Shared async checkpointer: StandardCheckpointer subclasses
# AsyncCheckpointer, so save() returns once arrays are snapshotted to host
# and the directory write proceeds on a background thread (a new save
# first waits for the previous one). SURVEY.md §5.3: async checkpointing
# is the explicit exceeds-parity goal here.
_ASYNC_CKPTR: ocp.StandardCheckpointer | None = None


def _async_ckptr() -> ocp.StandardCheckpointer:
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.StandardCheckpointer()
    return _ASYNC_CKPTR


def wait_for_saves() -> None:
    """Block until every async `save_params(..., wait=False)` has landed."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_params(path: str, params: Any, wait: bool = True) -> None:
    """Save a params pytree. ``wait=False`` returns as soon as the arrays
    are snapshotted (training continues while the write is in flight);
    call `wait_for_saves()` (or save again, or read back) to join."""
    ckptr = _async_ckptr()
    ckptr.save(_abs(path), to_savable(params), force=True)
    if wait:
        ckptr.wait_until_finished()


def load_params(path: str, like: Any | None = None) -> Any:
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        restored = ckptr.restore(_abs(path), to_savable(like))
        return from_savable(restored, like)
    return ckptr.restore(_abs(path))


def maybe_resume(ckpt: "CheckpointManager | None", state, replicate_fn=None):
    """Shared resume logic for every trainer.

    Checkpoints are keyed by EPOCH. Returns
    ``(state, start_epoch, global_step)`` — fresh-start values when there
    is nothing to restore. ``replicate_fn`` re-places the restored host
    arrays on the mesh.
    """
    if ckpt is None or ckpt.latest_step() is None:
        return state, 0, 0
    restored = ckpt.restore(state)
    if replicate_fn is not None:
        restored = replicate_fn(restored)
    start_epoch = ckpt.latest_step() + 1
    return restored, start_epoch, int(restored.step)


class BestTracker:
    """Best-metric model snapshotting that SURVIVES resume.

    The best params are written to ``<dir>/best_model`` the moment a new
    best appears (not only at exit), with the metric value in a sidecar
    json — so an interrupted run never loses an earlier, better model and
    a resumed run competes against the true best-so-far.
    """

    def __init__(self, save_dir: str | None, metric: str = "Recall@10"):
        self.dir = os.path.join(save_dir, "best_model") if save_dir else None
        self.meta = self.dir + ".json" if self.dir else None
        self.metric = metric
        self.value = -1.0
        if self.meta and os.path.exists(self.meta):
            import json

            with open(self.meta) as f:
                self.value = float(json.load(f)["value"])

    def update(self, value: float, params) -> bool:
        if value <= self.value:
            return False
        self.value = value
        if self.dir:
            import json

            # Synchronous on purpose: the sidecar must only ever describe
            # a DURABLE best_model dir. An async write here would let a
            # crash leave value=X on disk with no params — a resumed run
            # would then never re-save anything below X and the best model
            # is lost for good. Best-improvements are rare; the epoch-level
            # CheckpointManager saves are the async path.
            save_params(self.dir, params)
            with open(self.meta, "w") as f:
                json.dump({"metric": self.metric, "value": value}, f)
        return True

    def best_params(self, like):
        """Best params seen across ALL runs (disk), or None if none saved."""
        wait_for_saves()
        if self.dir and os.path.exists(self.dir) and self.value > -1.0:
            return load_params(self.dir, like=like)
        return None


class CheckpointManager:
    """Step-numbered training checkpoints with auto-resume.

    Covers (and exceeds — the reference has no auto-resume discovery) the
    `resume_from_checkpoint` flow of tiger_trainer.py:248-256.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            _abs(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(to_savable(state)))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(to_savable(state_like))
        )
        return from_savable(restored, state_like)

    def close(self) -> None:
        self._mgr.close()
