"""Checkpointing via orbax: ONE format for every model.

Replaces the reference's three coexisting ad-hoc formats (torch.save dicts,
bare state_dicts, HF save_pretrained dirs — SURVEY.md §5.4) with orbax
PyTree checkpoints. Semantic-id artifacts (the RQ-VAE -> downstream-dataset
interface, amazon.py:296-313) are a separate portable .npz — see
genrec_tpu.data.sem_ids.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp


def _abs(path: str) -> str:
    return os.path.abspath(path)


def _is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def to_savable(tree: Any) -> Any:
    """Host numpy copy of a pytree; typed PRNG keys become their uint32 data."""

    def conv(x):
        if _is_prng_key(x):
            return np.asarray(jax.random.key_data(x))
        return np.asarray(x)

    return jax.tree_util.tree_map(conv, tree)


def from_savable(saved: Any, like: Any) -> Any:
    """Re-wrap leaves that were PRNG keys in ``like``."""

    def conv(s, l):
        if _is_prng_key(l):
            return jax.random.wrap_key_data(jnp.asarray(s))
        return s

    return jax.tree_util.tree_map(conv, saved, like)


def save_params(path: str, params: Any) -> None:
    """Save a params pytree (host-side, synchronous)."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(_abs(path), to_savable(params), force=True)
    ckptr.wait_until_finished()


def load_params(path: str, like: Any | None = None) -> Any:
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        restored = ckptr.restore(_abs(path), to_savable(like))
        return from_savable(restored, like)
    return ckptr.restore(_abs(path))


class CheckpointManager:
    """Step-numbered training checkpoints with auto-resume.

    Covers (and exceeds — the reference has no auto-resume discovery) the
    `resume_from_checkpoint` flow of tiger_trainer.py:248-256.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            _abs(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(to_savable(state)))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(to_savable(state_like))
        )
        return from_savable(restored, state_like)

    def close(self) -> None:
        self._mgr.close()
