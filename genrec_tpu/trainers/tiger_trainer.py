"""TIGER trainer (parity target: reference genrec/trainers/tiger_trainer.py).

Loop shape mirrors the reference: epoch loop, AdamW + cosine warmup
schedule (:223-227), gradient accumulation (:126, 297) and clip-on-sync
(:313-318) — both folded into the single jitted step — and eval via
trie-constrained generate -> TopKAccumulator R@5/10, N@5/10 (:241-288).
The generate path is the jitted beam search of models/tiger.py; the trie
is built once from the dataset's item sem-ids.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import (
    batch_iterator,
    pack_examples,
    prefetch_eval_batches,
)
from genrec_tpu.data.tiger_seq import TigerSeqData, synthetic_tiger_data
from genrec_tpu.models.tiger import Tiger, tiger_generate
from genrec_tpu.ops.metrics import TopKAccumulator
from genrec_tpu.ops.schedules import cosine_schedule_with_warmup
from genrec_tpu.ops.trie import build_trie
from genrec_tpu.parallel import distributed_init, get_mesh, make_mesh


def make_generate_fn(model, trie, temperature, n_candidates):
    @jax.jit
    def gen(params, batch, rng):
        out = tiger_generate(
            model, params, trie,
            batch["user_ids"], batch["item_input_ids"], batch["token_type_ids"],
            batch["seq_mask"], rng,
            temperature=temperature, n_top_k_candidates=n_candidates,
        )
        return out.sem_ids

    return gen


def evaluate(gen_fn, params, arrays, batch_size, mesh, rng):
    acc = TopKAccumulator(ks=(5, 10))
    # Same prefetching iterator as the train loop: host batch assembly and
    # H2D transfer overlap the previous batch's generate.
    for sharded, host, valid in prefetch_eval_batches(
        batch_iterator(arrays, batch_size), mesh
    ):
        rng, sub = jax.random.split(rng)
        top = np.asarray(gen_fn(params, sharded, sub))  # (B, K, D)
        n = int(valid.sum())
        acc.accumulate(jnp.asarray(host["target_ids"][:n]), jnp.asarray(top[:n]))
    return acc.reduce(cross_process=True)


@configlib.configurable
def train(
    epochs=100,
    batch_size=256,
    learning_rate=1e-4,
    num_warmup_steps=100,
    weight_decay=0.035,
    gradient_accumulate_every=1,
    embedding_dim=128,
    attn_dim=384,
    dropout=0.1,
    num_heads=6,
    n_layers=8,
    sem_id_dim=3,
    codebook_size=256,
    max_items=20,
    num_user_embeddings=10_000,
    dataset="synthetic",
    dataset_folder="dataset/amazon",
    split="beauty",
    # Synthetic-dataset scale knob (tests/chaos harness shrink it).
    num_users=500,
    sem_ids_path=None,
    add_disambiguation=False,
    tensor_parallel=1,
    # First-fit-decreasing sequence packing of the ENCODER stream: several
    # (user token + history) examples share one row with segment-restricted
    # attention and within-segment T5 relative positions; decoders stay per
    # example, cross-attending into their own segment of the packed memory.
    # False restores the original one-example-per-row layout exactly.
    pack_sequences=True,
    # Decoder rows are sized rows x MAX-segments-per-row, so one dense row
    # of tiny histories would make every row pay for its segment count;
    # capping trades a little encoder occupancy for a bounded decoder batch
    # (measured on the Amazon-like distribution: cap 4 keeps occupancy
    # within a few percent and the packed step ~2x padded examples/sec).
    pack_max_segments=4,
    generate_temperature=0.2,
    do_eval=True,
    eval_every_epoch=10,
    eval_batch_size=64,
    # True (default): final valid/test run with the best-valid-Recall@10
    # snapshot (the sasrec/hstu reference protocol). False: final-epoch
    # weights — the reference TIGER trainer's protocol (it keeps no best
    # model, tiger_trainer.py:345); the parity harness uses this.
    test_on_best=True,
    save_dir_root="out/tiger",
    save_every_epoch=100,
    resume_from_checkpoint=False,
    wandb_logging=False,
    wandb_project="tiger_training",
    wandb_log_interval=100,
    amp=True,
    mixed_precision_type="bf16",
    profile_steps=0,
    seed=0,
):
    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    if tensor_parallel > 1:
        # 2-D mesh: batch on "data", vocab/embedding/FFN weights on "model"
        # (parallel/shardings.tiger_rules). XLA inserts the tp collectives.
        mesh = make_mesh({"data": -1, "model": tensor_parallel})
        logger.info(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    else:
        mesh = get_mesh()

    if dataset == "synthetic":
        data = synthetic_tiger_data(
            codebook_size=codebook_size, sem_id_dim=sem_id_dim,
            max_items=max_items, seed=seed, num_users=num_users,
        )
    else:
        from genrec_tpu.data.amazon import load_sequences
        from genrec_tpu.data.sem_ids import load_sem_ids

        seqs, _, _ = load_sequences(dataset_folder, split)
        if sem_ids_path is None:
            raise ValueError("amazon dataset needs sem_ids_path (RQ-VAE artifact)")
        sem_ids, codebook_size = load_sem_ids(sem_ids_path)
        if add_disambiguation:
            # Optional 4th code resolving sem-id collisions (reference
            # amazon.py:323-353; disabled in its shipped configs). The
            # rank-based PackedTrie handles the deeper id space.
            from genrec_tpu.data.sem_ids import dedup_sem_ids

            sem_ids = dedup_sem_ids(sem_ids, codebook_size)
        data = TigerSeqData(seqs, sem_ids, max_items=max_items,
                            user_hash_size=num_user_embeddings)
        sem_id_dim = data.D

    valid_arrays = data.eval_arrays("valid")
    test_arrays = data.eval_arrays("test")
    trie = build_trie(data.valid_item_sem_ids(), codebook_size)

    pack_row_len = 1 + max_items * sem_id_dim  # user token + item stream
    repack, train_arrays = None, None
    if pack_sequences:
        # Raw examples only — the padded (N, L) train matrix is never
        # materialized when the packer owns layout. Re-packed per epoch
        # (epoch-seeded shuffle) so example co-location is re-mixed like
        # the padded layout's per-epoch permutation; PackedTrainLoop
        # calls this lazily per epoch.
        examples = data.train_examples()

        def repack(epoch: int):
            return pack_examples(
                examples, row_len=pack_row_len,
                segment_keys=("target_ids",), max_segments=pack_max_segments,
                seed=(seed, epoch),
            )

    else:
        train_arrays = data.train_arrays()

    from genrec_tpu.core.checkpoint import BestTracker, CheckpointManager, save_params
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt = CheckpointManager(os.path.join(save_dir_root, "checkpoints")) if save_dir_root else None
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)
    # One optimizer step consumes batch_size * accum rows (packed rows
    # hold several examples each; state.step counts optimizer steps).
    rows_per_step = batch_size * gradient_accumulate_every
    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt,
        rows_per_step=rows_per_step, row_len=pack_row_len, seed=seed,
        pack_sequences=pack_sequences, repack=repack, train_arrays=train_arrays,
        # make_train_step MEANS aux over microbatches; scale real_tokens
        # back to whole-step counts.
        tokens_scale=float(gradient_accumulate_every),
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
    )
    # Accessing the report here materializes the epoch-0 pack (the jitted
    # loss closure below needs its rates before any resume decision), so a
    # resume at epoch E packs twice for TIGER — seconds, vs the ~30s+ step
    # recompile every restart pays anyway.
    pack_report = loop.pack_report

    compute_dtype = jnp.bfloat16 if (amp and mixed_precision_type == "bf16") else jnp.float32
    model = Tiger(
        embedding_dim=embedding_dim,
        attn_dim=attn_dim,
        dropout=dropout,
        num_heads=num_heads,
        n_layers=n_layers,
        num_item_embeddings=codebook_size,
        num_user_embeddings=num_user_embeddings,
        sem_id_dim=sem_id_dim,
        dtype=compute_dtype,
        # Round vocab/embedding rows up so the TP rules can actually shard
        # them (the natural flat vocab is odd); pad logits are masked.
        pad_vocab_to=tensor_parallel,
    )
    rng = jax.random.key(seed)
    init_rng, state_rng, eval_rng = jax.random.split(rng, 3)
    L = max_items * sem_id_dim
    params = model.init(
        init_rng,
        jnp.zeros((1,), jnp.int32),
        jnp.zeros((1, L), jnp.int32),
        jnp.zeros((1, L), jnp.int32),
        jnp.zeros((1, sem_id_dim), jnp.int32),
        jnp.zeros((1, sem_id_dim), jnp.int32),
        jnp.ones((1, L), jnp.int32),
    )["params"]

    n_train_rows = (
        pack_report.n_rows if pack_sequences
        else next(iter(train_arrays.values())).shape[0]
    )
    opt_steps_per_epoch = max(1, n_train_rows // rows_per_step)
    total_steps = epochs * opt_steps_per_epoch
    schedule = cosine_schedule_with_warmup(learning_rate, num_warmup_steps, total_steps)
    optimizer = optax.adamw(schedule, weight_decay=weight_decay)

    tgt_types = jnp.broadcast_to(jnp.arange(sem_id_dim), (1, sem_id_dim))

    if pack_sequences:
        # Expected examples per microbatch (static). make_train_step
        # averages microbatch losses with EQUAL weight; packed microbatches
        # carry varying example counts, so under accumulation each loss is
        # rescaled by actual/expected count — every example then weighs the
        # same in the averaged gradient (a fixed count makes this exact for
        # unpacked batches; accum=1 keeps the exact mean-over-valid loss).
        expected_per_micro = batch_size * pack_report.n_examples / pack_report.n_rows

        def loss_fn(params, batch, step_rng):
            out = model.apply(
                {"params": params},
                batch["item_input_ids"], batch["token_type_ids"],
                batch["user_token_ids"], batch["user_mask"],
                batch["segment_ids"], batch["positions"],
                batch["target_ids"], batch["segment_valid"],
                deterministic=False,
                rngs={"dropout": step_rng},
                method=Tiger.forward_packed,
            )
            loss = out.loss
            if gradient_accumulate_every > 1:
                count = jnp.sum(batch["segment_valid"]).astype(jnp.float32)
                loss = loss * count / expected_per_micro
            return loss, {"real_tokens": out.real_tokens.astype(jnp.float32)}
    else:
        def loss_fn(params, batch, step_rng):
            B = batch["user_ids"].shape[0]
            out = model.apply(
                {"params": params},
                batch["user_ids"], batch["item_input_ids"], batch["token_type_ids"],
                batch["target_ids"], jnp.broadcast_to(tgt_types, (B, sem_id_dim)),
                batch["seq_mask"],
                deterministic=False,
                rngs={"dropout": step_rng},
            )
            return out.loss, {}

    step_fn = jit_train_step(
        make_train_step(
            loss_fn, optimizer,
            accum_steps=gradient_accumulate_every, clip_norm=1.0,
        )
    )
    from genrec_tpu.parallel.shardings import make_place_state, tiger_rules

    place_state = make_place_state(
        mesh, tiger_rules() if tensor_parallel > 1 else None, log_fn=logger.info
    )
    state = place_state(TrainState.create(params, optimizer, state_rng))
    gen_fn = make_generate_fn(model, trie, generate_temperature, 10)

    start_epoch, start_batch, global_step = 0, 0, 0
    if resume_from_checkpoint:
        # Step-granular exact resume through the integrity ladder;
        # place_state preserves the tensor-parallel layout on restore.
        state, start_epoch, start_batch, global_step = loop.resume(state, place_state)
    best = BestTracker(save_dir_root)
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point; exit cleanly so the
            # scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return {}, {}

        if do_eval and (epoch + 1) % eval_every_epoch == 0:
            eval_rng, sub = jax.random.split(eval_rng)
            metrics = evaluate(gen_fn, state.params, valid_arrays, eval_batch_size, mesh, sub)
            logger.info(
                f"epoch {epoch} valid " + ", ".join(f"{k}={v:.4f}" for k, v in metrics.items())
            )
            tracker.log({"epoch": epoch, **{f"eval/{k}": v for k, v in metrics.items()}})
            best.update(metrics["Recall@10"], state.params)

        if ckpt is not None and (epoch + 1) % save_every_epoch == 0:
            # Epoch-boundary resume point: cursor = (next epoch, batch 0).
            loop.save(state, epoch=epoch + 1, next_batch=0, global_step=global_step)

    final_params = best.best_params(like=state.params) if test_on_best else None
    if final_params is None:
        final_params = state.params
    eval_rng, s1, s2 = jax.random.split(eval_rng, 3)
    valid_metrics = evaluate(gen_fn, final_params, valid_arrays, eval_batch_size, mesh, s1)
    test_metrics = evaluate(gen_fn, final_params, test_arrays, eval_batch_size, mesh, s2)
    logger.info("test " + ", ".join(f"{k}={v:.4f}" for k, v in test_metrics.items()))
    tracker.log({f"test/{k}": v for k, v in test_metrics.items()})
    if save_dir_root and best.value < 0:  # no eval ran: snapshot final params
        save_params(os.path.join(save_dir_root, "best_model"), final_params)
    loop.shutdown()
    return valid_metrics, test_metrics


# ---------------------------------------------------------------------------
# graftlint compile manifest (scripts/graftlint.py, docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

from genrec_tpu.analysis.manifest import BuiltEntry, register_entry


@register_entry("train/tiger_step", tags=("train",))
def _graftlint_entry() -> BuiltEntry:
    """CI-shape replica of this trainer's jitted step (unpacked path),
    SAME jit config as train() above (accum/clip flags, donate_argnums=0)."""
    import numpy as np

    model = Tiger(embedding_dim=16, attn_dim=32, dropout=0.0, num_heads=4,
                  n_layers=2, num_item_embeddings=8, num_user_embeddings=20,
                  sem_id_dim=3)
    D, B, items = 3, 4, 4
    L = items * D
    rng = np.random.default_rng(0)
    user = jnp.asarray(rng.integers(0, 20, (B,)), jnp.int32)
    ids = jnp.asarray(rng.integers(0, 8, (B, L)), jnp.int32)
    types = jnp.asarray(np.tile(np.arange(D), (B, items)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 8, (B, D)), jnp.int32)
    tgt_types = jnp.asarray(np.tile(np.arange(D), (B, 1)), jnp.int32)
    mask = jnp.ones((B, L), jnp.int32)
    params = model.init(
        jax.random.key(0), user, ids, types, tgt, tgt_types, mask
    )["params"]
    optimizer = optax.adamw(1e-3, weight_decay=0.01)

    def loss_fn(p, batch, step_rng):
        out = model.apply(
            {"params": p},
            batch["user_ids"], batch["item_input_ids"],
            batch["token_type_ids"], batch["target_ids"],
            batch["target_token_type_ids"], batch["seq_mask"],
            deterministic=False, rngs={"dropout": step_rng},
        )
        return out.loss, {}

    step_fn = jit_train_step(
        make_train_step(loss_fn, optimizer, accum_steps=1, clip_norm=1.0)
    )
    state = TrainState.create(params, optimizer, jax.random.key(1))
    batch = {
        "user_ids": user, "item_input_ids": ids, "token_type_ids": types,
        "target_ids": tgt, "target_token_type_ids": tgt_types,
        "seq_mask": mask,
    }
    return BuiltEntry(fn=step_fn, args=(state, batch), expect_donated=(0,))


if __name__ == "__main__":
    configlib.parse_config()
    train()
