"""Shared step-granular train loop: one implementation of the plumbing
that was triplicated (with drift) across the sasrec/hstu/tiger trainers
and hand-rolled (epoch-granular, with preemption holes) in the
cobra/lcrec/notellm/rqvae trainers —

- the per-epoch repack closure (epoch-seeded `pack_examples` so example
  co-location re-mixes like the padded layout's per-epoch permutation);
- device-scalar epoch loss / real-token accumulation (float() only at
  logging boundaries, so the host never blocks async dispatch);
- the examples-per-step timer math (seq/s keeps meaning EXAMPLES when a
  packed row holds several) and the occupancy epilogue;
- wandb-interval step logging and ProfileWindow ticks —

plus the STEP-GRANULAR fault tolerance this PR adds, which lands here
once instead of three times:

- the PreemptionGuard is polled after every optimizer step; on fire, a
  resume point (full TrainState + data-iterator cursor,
  `core.fault_tolerance.save_resume_point`) is written durably and the
  epoch returns ``preempted=True`` — a resumed run continues at the
  exact next batch with identical losses/grads;
- `core.chaos` hooks (signal injection, NaN batch poisoning) run inside
  the same loop that serves production, so chaos tests exercise the real
  code path;
- the `NonFiniteMonitor` consumes the jitted non-finite guard's metrics
  (one step deferred — no dispatch stall), dumps offending batches, and
  aborts after N consecutive skipped steps;
- multi-host preemption agreement: each host polls its local
  `PreemptionGuard`, but the loop acts on the fleet-wide OR
  (`parallel.any_across_processes`) so every host writes its resume
  point at the SAME global step — one host checkpointing step N while
  another runs on to N+1 would deadlock the next collective and fork
  the saved state. Single-process runs short-circuit to the local flag
  (no collective); multi-host runs poll the collective OR every
  ``preempt_poll_interval`` steps (lockstep on every host) so the hot
  loop never blocks on an every-step allgather.

The epoch-granularity trainers plug in through three knobs:
``pack_sequences=False`` + ``train_arrays`` (fixed padded layout),
``step_log`` (trainer-specific wandb metric dicts), and ``step_hook`` +
``run_epoch(max_steps=...)`` (rqvae's iteration-gated eval/save cadence
and iteration-count stop).

Observability (genrec_tpu/obs, landing here once for all seven
trainers): every epoch's wall time is classified into goodput buckets
(compute / compile / checkpoint-save / restore / data-wait /
nonfinite-skipped / preemption-drain / other) and reported per epoch —
fleet-aggregated on multi-host; XLA compile events are tapped during
step dispatch so an unexpected mid-run recompile is counted and logged
the step it happens; and the crash flight recorder is pointed at
``<save_dir_root>/flight_recorder.json`` so a SIGTERM'd or crashed run
leaves a structured post-mortem. See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from genrec_tpu.core import chaos
from genrec_tpu.core.fault_tolerance import (
    NonFiniteMonitor,
    resume_exact,
    save_resume_point,
)
from genrec_tpu.core.logging import log_goodput, log_occupancy
from genrec_tpu.core.profiling import StepTimer, log_epoch_perf
from genrec_tpu.data.batching import batch_iterator, prefetch_to_device
from genrec_tpu.obs.flight_recorder import get_flight_recorder
from genrec_tpu.obs.goodput import CompileEvents, GoodputMeter, fleet_goodput
from genrec_tpu.obs.memory import device_memory_stats
from genrec_tpu.obs.spans import NULL_TRACER


@dataclasses.dataclass
class EpochResult:
    state: Any
    global_step: int
    preempted: bool
    n_batches: int


class PackedTrainLoop:
    """Owns epoch execution for one trainer; the trainer keeps ownership
    of eval, best-model tracking, and periodic checkpoint CADENCE.

    ``repack(epoch) -> (arrays, PackingReport)`` is called lazily per
    epoch when ``pack_sequences``; otherwise ``train_arrays`` is the
    fixed padded layout. ``rows_per_step`` is the batch rows consumed per
    optimizer step (batch_size, times grad-accum for TIGER);
    ``tokens_scale`` rescales the step's mean ``real_tokens`` metric back
    to whole-step tokens under accumulation. ``examples_per_row``
    rescales seq/s for layouts whose rows hold a fixed number of
    examples (NoteLLM: 2 per pair-unit row). ``step_log(metrics,
    global_step) -> dict`` replaces the default wandb-interval payload;
    ``step_hook(state, epoch, next_batch, global_step)`` runs after
    every step (rqvae's iteration-gated eval/save).
    """

    def __init__(
        self,
        *,
        logger,
        tracker,
        prof,
        mesh,
        guard=None,
        ckpt=None,
        rows_per_step: int,
        row_len: int,
        seed: int,
        pack_sequences: bool,
        repack: Callable[[int], tuple[dict, Any]] | None = None,
        train_arrays: dict | None = None,
        tokens_scale: float = 1.0,
        examples_per_row: float = 1.0,
        wandb_log_interval: int = 100,
        save_dir_root: str | None = None,
        max_consecutive_nonfinite: int = 3,
        step_log: Callable[[dict, int], dict] | None = None,
        step_hook: Callable[[Any, int, int, int], None] | None = None,
        preempt_poll_interval: int = 8,
        tracer=None,
    ):
        if pack_sequences and repack is None:
            raise ValueError("pack_sequences=True needs a repack closure")
        if not pack_sequences and train_arrays is None:
            raise ValueError("pack_sequences=False needs train_arrays")
        self.logger = logger
        self.tracker = tracker
        self.prof = prof
        self.mesh = mesh
        self.guard = guard
        self.ckpt = ckpt
        self.rows_per_step = rows_per_step
        self.row_len = row_len
        self.seed = seed
        self.pack_sequences = pack_sequences
        self._repack = repack
        self.tokens_scale = tokens_scale
        self.examples_per_row = examples_per_row
        self.wandb_log_interval = wandb_log_interval
        self.step_log = step_log
        self.step_hook = step_hook
        self.preempt_poll_interval = max(1, int(preempt_poll_interval))
        self.monitor = NonFiniteMonitor.for_run(
            save_dir_root, logger, max_consecutive_nonfinite
        )
        # Observability (genrec_tpu/obs): goodput buckets per epoch, the
        # process-wide XLA compile tap (unexpected mid-run recompiles are
        # counted + logged the step they happen), optional span tracing,
        # and the crash flight recorder pointed at the run directory.
        self.goodput = GoodputMeter()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.recompiles = 0
        self._compile_events = CompileEvents.ensure()
        self._steps_run = 0
        self._in_preempt = False
        self._flight = get_flight_recorder()
        if save_dir_root:
            self._flight.configure(
                os.path.join(save_dir_root, "flight_recorder.json"),
                run_dir=save_dir_root,
            )
        self._ran_epoch = False
        self._arrays = train_arrays
        self._arrays_epoch: int | None = None
        self._report = None

    # -- layout ------------------------------------------------------------

    def _arrays_for(self, epoch: int) -> dict:
        # Lazy: a run resumed at epoch E packs ONCE (for E), not
        # epoch-0-then-E — restart latency sits inside the preemption
        # grace window on large datasets.
        if self.pack_sequences and self._arrays_epoch != epoch:
            self._arrays, rep = self._repack(epoch)
            self._arrays_epoch = epoch
            if self._report is None:
                # Rates only (n_examples/n_rows for timers): the example
                # multiset is epoch-invariant, so any epoch's report works.
                self._report = rep
                self.logger.info(str(rep))
        return self._arrays

    @property
    def pack_report(self):
        if self.pack_sequences and self._report is None:
            self._arrays_for(0)
        return self._report

    @property
    def examples_per_step(self) -> float:
        """MEAN examples per optimizer step: packed rows hold several
        examples, so seq/s keeps meaning sequences, not rows."""
        if self.pack_sequences:
            rep = self.pack_report
            return self.rows_per_step * rep.n_examples / rep.n_rows
        return float(self.rows_per_step) * self.examples_per_row

    def fleet_preempted(self, global_step: int | None = None) -> bool:
        """Fleet-wide preemption agreement: True iff ANY host's guard
        latched. Acting on the OR keeps all hosts preempting at the same
        global step instead of forking. Single-process: the local flag,
        no collective. Multi-host: a host-blocking allgather every step
        would serialize the hot loop against the fleet, so with a
        ``global_step`` the collective only runs every
        ``preempt_poll_interval`` steps — global_step advances in
        lockstep on every host, so all hosts poll (and so agree) at the
        same steps, and a latched signal is acted on within the interval
        (well inside any preemption grace window). Callers without a
        step (epoch boundaries) always poll."""
        if self.guard is None:
            return False
        if jax.process_count() == 1:
            return bool(self.guard.fired)
        if (
            global_step is not None
            and global_step % self.preempt_poll_interval != 0
        ):
            return False
        from genrec_tpu.parallel import any_across_processes

        return any_across_processes(self.guard.fired)

    def _note_compile(self, n: int, seconds: float, global_step: int) -> None:
        """Compile events observed during step dispatch. The run's FIRST
        step compiles by design; any later one is an unexpected mid-run
        recompile (shape drift, donation mismatch, cache eviction) —
        counted, logged at warning, and flight-recorded, the same
        discipline serving gets from check_serving_hlo."""
        if self._steps_run == 0:
            self.logger.info(
                f"step {global_step}: compiled train step "
                f"({n} XLA compile(s), {seconds:.1f}s)"
            )
            return
        self.recompiles += n
        self.logger.warning(
            f"step {global_step}: UNEXPECTED mid-run XLA recompile "
            f"({n} compile(s), {seconds:.2f}s; {self.recompiles} total this "
            "run) — a static shape or donation contract broke"
        )
        self._flight.record("recompile", step=global_step, n=n,
                            seconds=seconds)
        self.tracker.log({
            "global_step": global_step, "perf/recompiles": self.recompiles,
        })

    # -- resume + checkpoint -----------------------------------------------

    def resume(self, state_like, place_fn=None) -> tuple[Any, int, int, int]:
        """(state, start_epoch, start_batch, global_step) — exact cursor
        via the integrity ladder, or fresh-start values."""
        with self.goodput.measure("restore"):
            point = resume_exact(
                self.ckpt, state_like, place_fn,
                data_seed=self.seed, logger=self.logger,
            )
        if point is None:
            return state_like, 0, 0, 0
        self._flight.record(
            "resume", epoch=point.epoch, next_batch=point.next_batch,
            global_step=point.global_step,
        )
        return point.state, point.epoch, point.next_batch, point.global_step

    def save(self, state, *, epoch: int, next_batch: int, global_step: int,
             wait: bool = False) -> None:
        """Write a resume point (no-op without a checkpoint manager)."""
        if self.ckpt is not None:
            # Goodput: a preemption save is drain work, not the periodic
            # checkpoint cadence — classify by WHY it is being written.
            bucket = "preemption_drain" if self._in_preempt else "checkpoint_save"
            with self.goodput.measure(bucket):
                save_resume_point(
                    self.ckpt, state, epoch=epoch, next_batch=next_batch,
                    global_step=global_step, data_seed=self.seed, wait=wait,
                )

    def shutdown(self, preempted_epoch: int | None = None) -> None:
        """Close everything the loop owns (ckpt manager joins in-flight
        async saves, guard restores signal handlers, profiler and tracker
        flush) — the single exit sequence for both the preempted and the
        normal return paths of every packed trainer."""
        if self.ckpt is not None:
            self.ckpt.close()
        if self.guard is not None:
            self.guard.close()
        self.prof.close()
        run = self.goodput.run_report()
        if run["wall_s"] > 0 and self._steps_run:
            mem = device_memory_stats()
            peak = mem.get("peak_bytes_in_use")
            self.logger.info(
                f"run goodput {run['goodput_pct']:.1f}% over "
                f"{run['wall_s']:.1f}s wall (see goodput/* metrics)"
                + (f"; peak device memory {peak / 2**20:.1f} MB"
                   if peak else "")
            )
        self.tracker.finish()
        self._flight.record(
            "run_shutdown", preempted_epoch=preempted_epoch,
            steps_run=self._steps_run, recompiles=self.recompiles,
        )
        if preempted_epoch is not None:
            self.logger.info(
                f"preempted: exiting during epoch {preempted_epoch}"
            )

    def _preempt(self, state, epoch: int, next_batch: int, global_step: int):
        # Durable save FIRST: the monitor's deferred check may abort the
        # run (NonFiniteLossError), and a preemption arriving on top of a
        # non-finite streak must still leave a resume point — the streak
        # itself is inside the saved state (nonfinite_count), so the
        # resumed run keeps counting toward the threshold.
        self._flight.record("preempt", epoch=epoch, next_batch=next_batch,
                            global_step=global_step)
        self._in_preempt = True
        try:
            self.save(state, epoch=epoch, next_batch=next_batch,
                      global_step=global_step, wait=True)
            self.logger.info(
                f"preempted: resume point at epoch {epoch} batch {next_batch} "
                f"(global step {global_step})"
            )
            with self.goodput.measure("preemption_drain"):
                self.monitor.flush()
        finally:
            self._in_preempt = False
            # The dump the PreemptionGuard wrote at signal receipt
            # predates the resume point; re-dump so the post-mortem's
            # last events show the drain completing.
            self._flight.dump(reason="preemption_drain")

    # -- the epoch ---------------------------------------------------------

    def run_epoch(self, state, step_fn, epoch: int, global_step: int,
                  start_batch: int = 0,
                  max_steps: int | None = None) -> EpochResult:
        """One epoch (or its remainder from ``start_batch``), polling the
        guard per step (fleet-wide OR on multi-host). Returns with
        ``preempted=True`` after writing a durable mid-epoch resume
        point. ``max_steps`` stops before the batch that would push
        ``global_step`` past it (rqvae's iteration mode)."""
        if self.fleet_preempted():
            # Fired between epochs (eval/checkpoint window): the cursor
            # is simply "this epoch, batch start_batch".
            self._preempt(state, epoch, start_batch, global_step)
            return EpochResult(state, global_step, True, 0)
        arrays = self._arrays_for(epoch)
        timer = StepTimer(
            self.examples_per_step,
            skip_first=0 if self._ran_epoch else 1,
        )
        self._ran_epoch = True
        self._flight.record("epoch_start", epoch=epoch,
                            global_step=global_step, start_batch=start_batch)
        skipped_before = self.monitor.skipped_steps
        epoch_loss, epoch_tokens, n_batches = None, None, 0
        consumed = start_batch
        batches = iter(prefetch_to_device(
            chaos.poison_batches(
                batch_iterator(
                    arrays, self.rows_per_step, shuffle=True, seed=self.seed,
                    epoch=epoch, drop_last=True, start_batch=start_batch,
                ),
                start_step=global_step,
            ),
            self.mesh,
        ))
        while True:
            # Goodput: time blocked on the input pipeline (data_wait) is
            # measured apart from the step section, whose residual after
            # compile/skipped attribution is the compute bucket.
            t_wait = time.perf_counter()
            try:
                sharded, _ = next(batches)
            except StopIteration:
                break
            self.goodput.add("data_wait", time.perf_counter() - t_wait)
            if max_steps is not None and global_step >= max_steps:
                break
            t_step = time.perf_counter()
            c_n0, c_s0 = self._compile_events.snapshot()
            state, m = step_fn(state, sharded)
            c_n1, c_s1 = self._compile_events.snapshot()
            # Guard-skipped steps contribute 0 to the epoch mean — one
            # NaN batch must not turn the whole epoch summary NaN (NaN*0
            # is still NaN, so select, don't scale; the per-step wandb
            # log still reports the raw loss).
            loss = m["loss"]
            if "nonfinite" in m:
                loss = jnp.where(m["nonfinite"] > 0, 0.0, loss)
            epoch_loss = loss if epoch_loss is None else epoch_loss + loss
            if "real_tokens" in m:
                tok = m["real_tokens"] * self.tokens_scale
                epoch_tokens = tok if epoch_tokens is None else epoch_tokens + tok
            timer.tick()
            n_batches += 1
            consumed += 1
            global_step += 1
            self.prof.tick(global_step)
            if c_n1 > c_n0:
                self._note_compile(c_n1 - c_n0, c_s1 - c_s0, global_step)
            if global_step % self.wandb_log_interval == 0:
                self.tracker.log(
                    self.step_log(m, global_step)
                    if self.step_log is not None
                    else {"global_step": global_step,
                          "train/loss": float(m["loss"])}
                )
            # Deferred non-finite policy: checks the PREVIOUS step's flag.
            self.monitor.observe(global_step, epoch, m, sharded)
            # Step section closes here: observe() synced on the previous
            # step's device scalar, so this interval really holds device
            # compute. step_hook (rqvae's iteration-gated eval/save) and
            # the preemption poll land in `other`.
            t_done = time.perf_counter()
            self.goodput.note_step(t_done - t_step,
                                   compile_seconds=c_s1 - c_s0)
            self._steps_run += 1
            self._flight.record("step", step=global_step, epoch=epoch)
            if self.tracer.enabled:
                self.tracer.record_span(
                    "train_step", f"train-e{epoch}", t_step, t_done,
                    step=global_step,
                )
            if self.step_hook is not None:
                self.step_hook(state, epoch, consumed, global_step)
            chaos.maybe_kill(step=global_step)
            if self.fleet_preempted(global_step):
                self._preempt(state, epoch, consumed, global_step)
                return EpochResult(state, global_step, True, n_batches)
        self.monitor.flush()
        # Fault-injection hook (core.chaos): deliver a real signal in the
        # between-epoch eval/checkpoint window — the top-of-epoch
        # preemption branch above is what catches it on the NEXT call.
        # One hook here covers all seven trainers; no-op outside a plan.
        chaos.maybe_kill(epoch=epoch)
        if n_batches:
            # Zero batches = an epoch resumed exactly at its end (the
            # preemption latched after the final batch): nothing ran, so
            # logging a fabricated 0.0 epoch loss would be a lie.
            log_epoch_perf(
                self.logger, self.tracker, epoch, epoch_loss, n_batches, timer,
                tokens_per_step=(
                    float(epoch_tokens) / n_batches
                    if epoch_tokens is not None else None
                ),
            )
            if epoch_tokens is not None:
                log_occupancy(
                    self.logger, self.tracker, epoch, float(epoch_tokens),
                    n_batches * self.rows_per_step * self.row_len,
                )
            # Goodput: classify this epoch window's wall time and report
            # it; on a fleet, also the all-host aggregate (collective —
            # epochs end in lockstep, so every host reaches this line).
            self.goodput.note_skipped(
                self.monitor.skipped_steps - skipped_before
            )
            report = self.goodput.end_epoch()
            # Peak device bytes ride the goodput summary where the
            # backend exposes allocator stats (TPU/GPU; CPU has none) —
            # the trainers' view of the same HBM lever the serving
            # ledger budgets (obs/memory.py).
            mem = device_memory_stats()
            if mem.get("peak_bytes_in_use"):
                report["peak_device_bytes"] = mem["peak_bytes_in_use"]
            log_goodput(self.logger, self.tracker, epoch, report)
            if jax.process_count() > 1:
                # obs imports nothing upward (graftlint layering): the
                # collective is injected from the runtime layer here.
                from genrec_tpu.parallel.mesh import allgather_host_ints

                log_goodput(self.logger, self.tracker, epoch,
                            fleet_goodput(report, allgather_host_ints),
                            fleet=True)
        self._flight.record("epoch_end", epoch=epoch, global_step=global_step,
                            n_batches=n_batches)
        return EpochResult(state, global_step, False, n_batches)
