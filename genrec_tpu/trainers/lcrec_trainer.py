"""LCRec trainer (parity target: reference genrec/trainers/lcrec_trainer.py).

Epoch loop, AdamW + cosine schedule, optional LoRA (:306-315), SFT with
prompt-masked labels, constrained beam-10 generation eval producing
per-codebook + exact-match + TopK metrics (:131-267), eval_only mode
(:358-364). The constrained decode is the jitted cascade of
models/lcrec.py instead of an HF prefix_allowed_tokens_fn host callback.

The "amazon" dataset path expects a local HF Qwen checkpoint + tokenizer
(zero-egress environments use the synthetic path, which exercises the
identical code on a tiny random-init backbone).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.lora import lora_init, lora_merge, lora_param_count
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import (
    batch_iterator,
    prefetch_eval_batches,
    prefetch_to_device,
)
from genrec_tpu.data.lcrec_tasks import synthetic_lcrec_data
from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.lcrec import (
    extend_vocab,
    generate_greedy,
    generate_topk_constrained,
    sft_loss,
)
from genrec_tpu.ops.metrics import TopKAccumulator
from genrec_tpu.ops.schedules import cosine_schedule_with_warmup
from genrec_tpu.parallel import distributed_init, get_mesh


def make_generate_fn(model, base_vocab, num_codebooks, codebook_size, beam_width, max_cache):
    @jax.jit
    def gen(params, batch):
        out = generate_topk_constrained(
            model, params, batch["input_ids"], batch["attention_mask"],
            base_vocab, num_codebooks, codebook_size,
            beam_width=beam_width, max_cache=max_cache,
        )
        return out.sem_ids

    return gen


def evaluate_item2index(gen_fn, params, arrays, batch_size, mesh, num_codebooks):
    """Greedy constrained item->index over the item set: exact-match +
    per-codebook accuracy (reference lcrec_trainer.py:193-213)."""
    from genrec_tpu.parallel import metric_allreduce

    correct = np.zeros(num_codebooks)
    exact = 0
    total = 0
    # Eval uses the same prefetching iterator as the train loop so host
    # batching + H2D transfer overlap the previous batch's generate.
    for sharded, host, valid in prefetch_eval_batches(
        batch_iterator(arrays, batch_size), mesh
    ):
        top = np.asarray(gen_fn(params, sharded))  # (B, W, C)
        n = int(valid.sum())
        pred = top[:n, 0, :]
        target = host["target_ids"][:n]
        correct += (pred == target).sum(axis=0)
        exact += int((pred == target).all(axis=1).sum())
        total += n
    s = metric_allreduce(
        {"correct": list(correct), "exact": float(exact), "total": float(total)}
    )
    out = {"item2index_exact": s["exact"] / max(s["total"], 1)}
    out.update(
        {
            f"item2index_c{c}": s["correct"][c] / max(s["total"], 1)
            for c in range(num_codebooks)
        }
    )
    return out


def evaluate_index2item(free_fn, params, arrays, target_texts, batch_size, mesh, tok):
    """Unconstrained index->item: generated text must contain the target
    title (reference lcrec_trainer.py:215-227)."""
    from genrec_tpu.parallel import metric_allreduce

    match = 0
    total = 0
    offset = 0
    for sharded, valid in prefetch_to_device(batch_iterator(arrays, batch_size), mesh):
        toks = np.asarray(free_fn(params, sharded))  # (B, T)
        n = int(valid.sum())
        for i in range(n):
            tgt = target_texts[offset + i].strip().lower()
            gen = tok.decode(toks[i]).strip().lower()
            if tgt and gen and tgt in gen:
                match += 1
        total += n
        offset += n
    s = metric_allreduce({"match": float(match), "total": float(total)})
    return {"index2item_match": s["match"] / max(s["total"], 1)}


def evaluate(gen_fn, params, arrays, batch_size, mesh, num_codebooks):
    from genrec_tpu.parallel import metric_allreduce

    acc = TopKAccumulator(ks=(1, 5, 10))
    cb_correct = np.zeros(num_codebooks)
    cb_total = 0
    for sharded, host, valid in prefetch_eval_batches(
        batch_iterator(arrays, batch_size), mesh
    ):
        top = np.asarray(gen_fn(params, sharded))
        n = int(valid.sum())
        target = host["target_ids"][:n]
        acc.accumulate(jnp.asarray(target), jnp.asarray(top[:n]))
        top1 = top[:n, 0, :]
        for c in range(num_codebooks):
            cb_correct[c] += (top1[:, c] == target[:, c]).sum()
        cb_total += n
    m = acc.reduce(cross_process=True)
    # Codebook counters must be summed across hosts too, same scope as
    # the TopK metrics.
    cb = metric_allreduce({"correct": list(cb_correct), "total": float(cb_total)})
    m.update(
        {
            f"codebook_acc_{c}": cb["correct"][c] / max(cb["total"], 1)
            for c in range(num_codebooks)
        }
    )
    return m


@configlib.configurable
def train(
    epochs=4,
    batch_size=8,
    learning_rate=3e-4,
    num_warmup_steps=20,
    weight_decay=0.01,
    num_codebooks=3,
    codebook_size=8,
    beam_width=10,
    max_text_len=96,
    use_lora=False,
    gradient_checkpointing=False,
    # Fused full-softmax CE over the LM head (kernels/fused_ce.py): the
    # (B, L, vocab) logits — the largest activation of the SFT step at
    # real Qwen vocab (~150k) — never materialize. Exact same loss.
    # auto = on when on TPU; dense (non-sp/pp) loss path only.
    use_fused_ce="auto",
    # >1: shard the token dim over an "sp" mesh axis and train with ring
    # attention (long-context path; max_text_len must divide by it).
    sequence_parallel=1,
    # >1: GPipe pipeline parallelism over a "pipe" mesh axis — the block
    # stack is stage-sharded, activations ppermute between stages
    # (parallel/pipeline.py). n_layers must divide by it.
    pipeline_parallel=1,
    pp_microbatches=None,
    # >1: Megatron-style tensor parallelism over a "model" mesh axis
    # (parallel/shardings.qwen_rules: column q/k/v/gate/up, row o/down,
    # vocab-sharded embedding/head where divisible).
    tensor_parallel=1,
    lora_rank=8,
    lora_alpha=16.0,
    lora_targets=("q_proj", "v_proj"),
    # >0: replace the dense SwiGLU with a routed mixture of experts
    # (backbones.qwen.QwenMoEMLP — beyond-parity, reference has no MoE).
    num_experts=0,
    num_experts_per_tok=2,
    # >1: shard the expert stacks over an "expert" mesh axis
    # (parallel/shardings.moe_rules); requires num_experts % it == 0.
    expert_parallel=1,
    # Backbone (synthetic default: tiny random-init Qwen).
    pretrained_path=None,
    hidden_size=64,
    intermediate_size=128,
    n_layers=2,
    num_heads=4,
    num_kv_heads=2,
    dataset="synthetic",
    dataset_folder="dataset/amazon",
    split="beauty",
    sem_ids_path=None,
    # History window for the task prompts (reference lcrec max_seq_len,
    # amazon_lcrec.py:183 — caps every seqrec/fusionseqrec/itemsearch
    # history and the eval history); amazon dataset path only.
    max_history=20,
    # Training samples drawn per user per task stream (our sampler's
    # budget knob; the reference generates per-position samples and caps
    # with max_train_samples instead).
    samples_per_user=2,
    # Sampling weights over data.lcrec_tasks.TASKS (seqrec, item2index,
    # index2item, fusionseqrec, itemsearch, preferenceobtain); None = the
    # reference's default mix. The debug config pins seqrec-only, matching
    # reference AmazonLCRecDataset.enabled_tasks=["seqrec"].
    task_weights=None,
    eval_item_tasks=True,
    eval_items_limit=256,
    index2item_max_new=16,
    do_eval=True,
    eval_only=False,
    # Debug fast mode (reference lcrec_trainer.py:283, 327-333 /
    # lcrec_debug.gin): 0 = no limit.
    max_train_samples=0,
    max_eval_samples=0,
    resume_from_checkpoint=False,
    # True: final evals use the best-valid-Recall@10 weights (the
    # sasrec/hstu reference protocol). False: final-epoch weights — the
    # reference LCRec protocol (lcrec_trainer.py:426-431 saves final only,
    # no best tracking); the parity harness uses False.
    test_on_best=True,
    eval_every_epoch=2,
    eval_batch_size=16,
    save_dir_root="out/lcrec",
    save_every_epoch=10,
    wandb_logging=False,
    wandb_project="lcrec_training",
    wandb_log_interval=50,
    amp=True,
    mixed_precision_type="bf16",
    profile_steps=0,
    seed=0,
):
    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    chosen = [n for n in (sequence_parallel, pipeline_parallel, tensor_parallel,
                          expert_parallel)
              if n > 1]
    # Wired composition #1: tensor x expert parallelism for MoE runs
    # (dp x model x expert — the standard MoE-LLM layout: attention
    # Megatron-sharded, expert stacks expert-sharded; the rule sets match
    # disjoint param paths so they concatenate).
    tp_ep_combo = (
        tensor_parallel > 1 and expert_parallel > 1 and num_experts > 0
        and sequence_parallel == 1 and pipeline_parallel == 1
    )
    # Wired composition #2 — dp x tp x pp: the standard dense-LLM pod
    # layout. The pipeline
    # shard_map goes manual over pipe/data only; the model axis stays
    # auto and XLA Megatron-shards the per-stage matmuls from the
    # qwen_rules constraints (parallel/pipeline.py make_pp_sft_loss).
    tp_pp_combo = (
        tensor_parallel > 1 and pipeline_parallel > 1
        and sequence_parallel == 1 and expert_parallel == 1
        and num_experts == 0
    )
    if len(chosen) > 1 and not (tp_ep_combo or tp_pp_combo):
        raise ValueError("pick ONE of sequence_parallel / pipeline_parallel / "
                         "tensor_parallel / expert_parallel per run (wired "
                         "compositions: tensor_parallel x expert_parallel "
                         "with num_experts>0, and tensor_parallel x "
                         "pipeline_parallel for the dense stack)")
    if num_experts > 0 and (sequence_parallel > 1 or pipeline_parallel > 1):
        # sp/pp run the blocks inside shard_map and do not collect the
        # sown router-aux loss. Refuse rather than quietly degrade.
        raise ValueError("num_experts>0 is wired for dp / expert_parallel / "
                         "tensor_parallel x expert_parallel runs only")
    if num_experts > 0 and tensor_parallel > 1 and expert_parallel == 1:
        # tp's qwen_rules match Dense kernels only, so the dominant
        # (E, D, F) expert stacks would silently stay replicated.
        raise ValueError("MoE with tensor_parallel needs expert_parallel>1 "
                         "too (else the expert stacks stay replicated)")
    if expert_parallel > 1 and use_lora:
        # Same reasoning as tensor_parallel+LoRA below: the trainable tree
        # is the adapters, moe_rules match nothing in it, and the expert
        # axis would just eat devices from data parallelism.
        raise ValueError("expert_parallel with use_lora is not wired; "
                         "run LoRA data-parallel")
    if expert_parallel > 1 and (
        num_experts <= 0 or num_experts % expert_parallel
    ):
        raise ValueError(
            f"expert_parallel={expert_parallel} needs num_experts>0 "
            f"divisible by it (got {num_experts})"
        )
    if tensor_parallel > 1 and use_lora:
        # The LoRA step rebuilds the merged tree per step from replicated
        # base_params, so TP would shard nothing (no memory benefit) while
        # the model axis still eats devices from data parallelism. Refuse
        # rather than silently run at 1/tp throughput.
        raise ValueError("tensor_parallel with use_lora is not wired; "
                         "run LoRA data-parallel (it is already memory-light)")
    if tp_ep_combo:
        from genrec_tpu.parallel import make_mesh

        mesh = make_mesh(
            {"data": -1, "model": tensor_parallel, "expert": expert_parallel}
        )
        logger.info(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    elif tp_pp_combo:
        from genrec_tpu.parallel import make_mesh

        mesh = make_mesh(
            {"data": -1, "model": tensor_parallel, "pipe": pipeline_parallel}
        )
        logger.info(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    elif chosen:
        from genrec_tpu.parallel import make_mesh

        axis = (
            ("sp", sequence_parallel) if sequence_parallel > 1
            else ("pipe", pipeline_parallel) if pipeline_parallel > 1
            else ("expert", expert_parallel) if expert_parallel > 1
            else ("model", tensor_parallel)
        )
        mesh = make_mesh({"data": -1, axis[0]: axis[1]})
        logger.info(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    else:
        mesh = get_mesh()
    compute_dtype = jnp.bfloat16 if (amp and mixed_precision_type == "bf16") else jnp.float32

    rng = jax.random.key(seed)
    init_rng, vocab_rng, state_rng = jax.random.split(rng, 3)

    # None = each data source's default mix.
    tw_extra = {} if task_weights is None else {"task_weights": tuple(task_weights)}
    if dataset == "synthetic":
        data, tok = synthetic_lcrec_data(
            codebook_size=codebook_size, num_codebooks=num_codebooks, seed=seed,
            **tw_extra,
        )
        data.max_len = max_text_len
        # Backbone vocab covers words only; codebook tokens are appended by
        # extend_vocab below, exactly like the HF resize path.
        cfg = QwenConfig(
            vocab_size=tok.base_vocab, hidden_size=hidden_size,
            intermediate_size=intermediate_size, num_hidden_layers=n_layers,
            num_attention_heads=num_heads, num_key_value_heads=num_kv_heads,
            max_position_embeddings=max_text_len + num_codebooks + 1,
            rope_theta=10000.0, tie_word_embeddings=False,
            num_experts=num_experts, num_experts_per_tok=num_experts_per_tok,
        )
        model0 = QwenLM(cfg, dtype=compute_dtype, remat=gradient_checkpointing,
                        expert_axis="expert" if expert_parallel > 1 else None)
        params = model0.init(init_rng, jnp.zeros((1, 4), jnp.int32))["params"]
    else:
        # Real-data path (reference amazon_lcrec.py:164-676): sequences +
        # meta text from the Amazon dump, sem ids from the RQ-VAE artifact,
        # HF tokenizer when pretrained_path provides one (WordTokenizer
        # fallback otherwise).
        from genrec_tpu.data.lcrec_tasks import amazon_lcrec_data

        if sem_ids_path is None:
            raise ValueError("amazon LCRec needs sem_ids_path (RQ-VAE artifact)")
        hf_tok = None
        if pretrained_path:
            from transformers import AutoTokenizer

            hf_tok = AutoTokenizer.from_pretrained(pretrained_path)
        data, tok = amazon_lcrec_data(
            dataset_folder, split, sem_ids_path,
            tokenizer=hf_tok, max_len=max_text_len,
            max_history=max_history, seed=seed, **tw_extra,
        )
        num_codebooks = int(data.sem_ids.shape[1])
        codebook_size = int(tok.codebook_size)
        max_pos = max_text_len + max(num_codebooks, index2item_max_new) + 1

        hf_config = os.path.join(pretrained_path or "", "config.json")
        if num_experts > 0 and pretrained_path and os.path.exists(hf_config):
            raise ValueError(
                "num_experts>0 with a full HF checkpoint is not wired "
                "(params_from_hf_state_dict maps dense Qwen2 only)"
            )
        if pretrained_path and os.path.exists(hf_config):
            # Full local checkpoint: convert torch weights into the flax
            # tree (backbones.qwen.params_from_hf_state_dict).
            import json as _json

            with open(hf_config) as f:
                hc = _json.load(f)
            cfg = QwenConfig(
                vocab_size=hc["vocab_size"],
                hidden_size=hc["hidden_size"],
                intermediate_size=hc["intermediate_size"],
                num_hidden_layers=hc["num_hidden_layers"],
                num_attention_heads=hc["num_attention_heads"],
                num_key_value_heads=hc.get(
                    "num_key_value_heads", hc["num_attention_heads"]
                ),
                max_position_embeddings=max(
                    max_pos, hc.get("max_position_embeddings", max_pos)
                ),
                rope_theta=hc.get("rope_theta", 1e6),
                rms_norm_eps=hc.get("rms_norm_eps", 1e-6),
                tie_word_embeddings=hc.get("tie_word_embeddings", True),
            )
            from transformers import AutoModelForCausalLM

            from genrec_tpu.models.backbones.qwen import params_from_hf_state_dict

            hf_model = AutoModelForCausalLM.from_pretrained(pretrained_path)
            sd = {k: v.numpy() for k, v in hf_model.state_dict().items()}
            del hf_model
            params = params_from_hf_state_dict(sd, cfg)
            params = jax.tree_util.tree_map(jnp.asarray, params)
            logger.info(f"loaded HF backbone from {pretrained_path}")
        else:
            # Tokenizer-only dir (or none): random-init backbone at the
            # configured dims, vocab sized to the tokenizer.
            cfg = QwenConfig(
                vocab_size=tok.base_vocab, hidden_size=hidden_size,
                intermediate_size=intermediate_size, num_hidden_layers=n_layers,
                num_attention_heads=num_heads, num_key_value_heads=num_kv_heads,
                max_position_embeddings=max_pos,
                rope_theta=10000.0, tie_word_embeddings=False,
                num_experts=num_experts,
                num_experts_per_tok=num_experts_per_tok,
            )
        model0 = QwenLM(cfg, dtype=compute_dtype, remat=gradient_checkpointing,
                        expert_axis="expert" if expert_parallel > 1 else None)
        params = (
            params
            if pretrained_path and os.path.exists(hf_config)
            else model0.init(init_rng, jnp.zeros((1, 4), jnp.int32))["params"]
        )

    # Append codebook special tokens (resize_token_embeddings equivalent).
    # base = first codebook-token id: the tokenizer's, when it has one (HF
    # models pad vocab past len(tokenizer), so cfg.vocab_size can differ).
    # Pad embed_tokens/lm_head rows to a multiple of lcm(8, tp): divisible
    # by the actual TP degree (including non-power-of-2 meshes) so the
    # qwen_rules vocab sharding never silently falls back to replication,
    # AND independent of tensor_parallel for every tp dividing 8, so a
    # checkpoint trained at one such degree restores/eval_only's at
    # another (pad rows are masked out of the loss by valid_vocab and out
    # of generation by valid_vocab/allowed slices).
    import math

    cfg, params, base_vocab = extend_vocab(
        cfg, params, num_codebooks, codebook_size, vocab_rng,
        base=getattr(tok, "base_vocab", None),
        pad_to=math.lcm(8, max(tensor_parallel, 1)),
    )
    # remat mirrors the reference's gradient_checkpointing_enable (lcrec.py:42-46).
    model = QwenLM(cfg, dtype=compute_dtype, remat=gradient_checkpointing,
                   expert_axis="expert" if expert_parallel > 1 else None)
    # Ids >= live_vocab are pad rows (TP padding / HF resize padding):
    # masked out of the SFT softmax and of generation argmax, so they stay
    # inert and tp>1 losses match tp=1 exactly.
    live_vocab = base_vocab + num_codebooks * codebook_size
    logger.info(
        f"vocab {base_vocab} + {num_codebooks * codebook_size} codebook tokens"
        + (f" (+{cfg.vocab_size - live_vocab} pad)" if cfg.vocab_size > live_vocab else "")
    )

    train_arrays = data.train_arrays(samples_per_user=samples_per_user)
    valid_arrays = data.eval_arrays("valid")
    test_arrays = data.eval_arrays("test")
    if max_train_samples > 0:
        train_arrays = {k: v[:max_train_samples] for k, v in train_arrays.items()}
        logger.info(f"limited train samples to {len(train_arrays['input_ids'])}")
    if max_eval_samples > 0:
        valid_arrays = {k: v[:max_eval_samples] for k, v in valid_arrays.items()}
        test_arrays = {k: v[:max_eval_samples] for k, v in test_arrays.items()}
        logger.info(f"limited eval samples to {len(valid_arrays['input_ids'])}")

    steps_per_epoch = max(1, len(train_arrays["input_ids"]) // batch_size)
    schedule = cosine_schedule_with_warmup(
        learning_rate, num_warmup_steps, epochs * steps_per_epoch
    )
    optimizer = optax.adamw(schedule, weight_decay=weight_decay)

    if sequence_parallel > 1:
        # Ring-attention loss over the sp-sharded token dim; generation
        # (KV-cache decode) stays on the plain model — same param tree.
        from genrec_tpu.models.lcrec import make_sp_sft_loss

        if max_text_len % sequence_parallel:
            raise ValueError(
                f"max_text_len {max_text_len} must divide by "
                f"sequence_parallel {sequence_parallel}"
            )
        _, base_loss = make_sp_sft_loss(
            cfg, mesh, dtype=compute_dtype, remat=gradient_checkpointing,
            valid_vocab=live_vocab,
        )
    elif pipeline_parallel > 1:
        from genrec_tpu.models.pp_sft import make_pp_sft_loss
        from genrec_tpu.parallel.shardings import qwen_rules as _qr

        base_loss = make_pp_sft_loss(
            cfg, mesh, n_micro=pp_microbatches, dtype=compute_dtype,
            remat=gradient_checkpointing, valid_vocab=live_vocab,
            tp_rules=_qr() if tp_pp_combo else None, log_fn=logger.info,
        )
    else:
        def _dense_sft_loss(fused: bool):
            return lambda p, batch: sft_loss(
                model, p, batch["input_ids"], batch["attention_mask"],
                batch["labels"], valid_vocab=live_vocab, use_fused_ce=fused,
            )

        if tensor_parallel > 1:
            # Vocab-sharded head: the dense fused kernel cannot be
            # GSPMD-partitioned over the vocab dim, so fused CE routes
            # through shard_map over the model axis instead (per-device
            # pallas_calls, per-shard softmax stats merged with pmax/psum).
            # Auto therefore needs no single-chip gate here — shard_map
            # never asks GSPMD to split the Mosaic call.
            if use_fused_ce == "auto":
                from genrec_tpu.kernels.policy import auto_sharded_fused_ce

                use_fused_ce = auto_sharded_fused_ce()
            if use_fused_ce:
                from genrec_tpu.models.lcrec import (
                    make_tp_sharded_fused_sft_loss,
                )

                base_loss = make_tp_sharded_fused_sft_loss(
                    model, mesh, valid_vocab=live_vocab
                )
            else:
                base_loss = _dense_sft_loss(False)
        else:
            if use_fused_ce == "auto":
                from genrec_tpu.kernels.policy import auto_fused_ce

                use_fused_ce = auto_fused_ce(tensor_parallel)
            base_loss = _dense_sft_loss(bool(use_fused_ce))

    if use_lora:
        lora = lora_init(params, jax.random.fold_in(rng, 7), lora_rank, tuple(lora_targets))
        logger.info(f"LoRA: {lora_param_count(lora)} trainable params")
        base_params = params

        def loss_fn(lp, batch, step_rng):
            merged = lora_merge(base_params, lp, lora_alpha, lora_rank)
            return base_loss(merged, batch), {}

        trainable = lora
        params_of = lambda tp: lora_merge(base_params, tp, lora_alpha, lora_rank)
    else:
        def loss_fn(p, batch, step_rng):
            return base_loss(p, batch), {}

        trainable = params
        params_of = lambda tp: tp

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=1.0))
    from genrec_tpu.parallel.shardings import make_place_state, moe_rules, qwen_rules

    rules = (
        tuple(qwen_rules()) + tuple(moe_rules()) if tp_ep_combo
        else qwen_rules() if tensor_parallel > 1
        else moe_rules() if expert_parallel > 1
        else None
    )
    place_state = make_place_state(mesh, rules, log_fn=logger.info)
    state = place_state(TrainState.create(trainable, optimizer, state_rng))
    gen_fn = make_generate_fn(
        model, base_vocab, num_codebooks, codebook_size, beam_width,
        max_cache=max_text_len + num_codebooks + 1,
    )
    if eval_item_tasks:
        # item2index (greedy constrained) + index2item (unconstrained)
        # evaluation over the item set (reference lcrec_trainer.py:193-227).
        i2i_arrays = data.item2index_eval_arrays(eval_items_limit)
        idx2i_arrays, idx2i_texts = data.index2item_eval_arrays(eval_items_limit)
        greedy_fn = make_generate_fn(
            model, base_vocab, num_codebooks, codebook_size, 1,
            max_cache=max_text_len + num_codebooks + 1,
        )
        free_fn = jax.jit(
            lambda p, b: generate_greedy(
                model, p, b["input_ids"], b["attention_mask"],
                index2item_max_new, tok.eos_id,
                max_cache=max_text_len + index2item_max_new,
                # Keep argmax off live HF vocab-padding rows the tokenizer
                # cannot decode.
                valid_vocab=tok.vocab_size,
            )
        )

    from genrec_tpu.core.checkpoint import BestTracker, CheckpointManager, save_params
    from genrec_tpu.core.fault_tolerance import restore_for_eval
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt = CheckpointManager(os.path.join(save_dir_root, "checkpoints")) if save_dir_root else None
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)
    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt,
        rows_per_step=batch_size, row_len=max_text_len, seed=seed,
        pack_sequences=False, train_arrays=train_arrays,
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
    )

    # eval_only restores the latest checkpoint (the reference loads a
    # trained model for eval_only, lcrec_trainer.py:358-364) WITHOUT the
    # exact-resume preconditions — a pure evaluation consumes no training
    # data, so a different data seed or a pre-PR4 record must not refuse;
    # resume picks up mid-training through the step-granular resume point.
    start_epoch, start_batch, global_step = 0, 0, 0
    if eval_only:
        state, ckpt_step = restore_for_eval(
            ckpt, state, place_state, logger=logger  # keep the TP layout
        )
        if ckpt_step is None:
            logger.warning("eval_only without a checkpoint: evaluating the INITIAL model")
    elif resume_from_checkpoint:
        state, start_epoch, start_batch, global_step = loop.resume(
            state, place_state  # restored runs keep the TP layout
        )

    if eval_only:
        m = evaluate(gen_fn, params_of(state.params), valid_arrays, eval_batch_size, mesh, num_codebooks)
        logger.info("eval_only " + ", ".join(f"{k}={v:.4f}" for k, v in m.items()))
        loop.shutdown()
        return m, m

    best = BestTracker(save_dir_root)
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point (even mid-FINAL-epoch — the
            # hole the old epoch-granular guard left open); exit cleanly
            # so the scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return {}, {}

        if ckpt is not None and (epoch + 1) % save_every_epoch == 0:
            # Epoch-boundary resume point: cursor = (next epoch, batch 0).
            loop.save(state, epoch=epoch + 1, next_batch=0,
                      global_step=global_step)

        if do_eval and (epoch + 1) % eval_every_epoch == 0:
            m = evaluate(gen_fn, params_of(state.params), valid_arrays, eval_batch_size, mesh, num_codebooks)
            logger.info(
                f"epoch {epoch} valid " + ", ".join(f"{k}={v:.4f}" for k, v in m.items())
            )
            tracker.log({"epoch": epoch, **{f"eval/{k}": v for k, v in m.items()}})
            best.update(m["Recall@10"], state.params)

    # Unconditional final resume point: closes the old hole where a
    # save_every_epoch cadence never firing left a completed run with
    # NOTHING on disk to resume from.
    loop.save(state, epoch=epochs, next_batch=0, global_step=global_step)
    final_trainable = (
        best.best_params(like=state.params) if test_on_best else None
    )
    if final_trainable is None:
        final_trainable = state.params
    final_params = params_of(final_trainable)
    valid_metrics = evaluate(gen_fn, final_params, valid_arrays, eval_batch_size, mesh, num_codebooks)
    test_metrics = evaluate(gen_fn, final_params, test_arrays, eval_batch_size, mesh, num_codebooks)
    if eval_item_tasks:
        test_metrics.update(
            evaluate_item2index(
                greedy_fn, final_params, i2i_arrays, eval_batch_size, mesh,
                num_codebooks,
            )
        )
        test_metrics.update(
            evaluate_index2item(
                free_fn, final_params, idx2i_arrays, idx2i_texts,
                eval_batch_size, mesh, tok,
            )
        )
    logger.info("test " + ", ".join(f"{k}={v:.4f}" for k, v in test_metrics.items()))
    tracker.log({f"test/{k}": v for k, v in test_metrics.items()})
    if save_dir_root:
        # Best tracker stores the TRAINABLE tree (lora or full); persist the
        # merged model too for direct consumption.
        save_params(os.path.join(save_dir_root, "final_model"), final_params)
    loop.shutdown()
    return valid_metrics, test_metrics


if __name__ == "__main__":
    configlib.parse_config()
    train()
