"""COBRA trainer (parity target: reference genrec/trainers/cobra_trainer.py).

Epoch loop, AdamW + cosine schedule, weighted sparse+dense loss
(:359-362); eval recomputes all item dense vecs from the current encoder
(:303-334), runs `beam_fusion` (n_beam=20, alpha=0.5) and accumulates
TopKAccumulator + per-codebook top-1 accuracy (:414-452).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import (
    batch_iterator,
    pad_to_batch,
    prefetch_eval_batches,
)
from genrec_tpu.data.cobra_seq import CobraSeqData, synthetic_cobra_data
from genrec_tpu.models.cobra import Cobra, beam_fusion
from genrec_tpu.ops.metrics import TopKAccumulator
from genrec_tpu.ops.schedules import cosine_schedule_with_warmup
from genrec_tpu.parallel import distributed_init, get_mesh, replicate


import functools


@functools.partial(jax.jit, static_argnums=0)
def _encode_items_jit(model, params, txt):
    return model.apply({"params": params}, txt[:, None, :], method=Cobra.encode_items)[:, 0]


def compute_item_dense_vecs(model, params, item_texts: np.ndarray, batch_size=256):
    """Dense vectors for every item from the CURRENT encoder (re-done each
    eval; reference cobra_trainer.py:303-334). The jit is cached on
    (model, shapes), so repeat evals don't recompile."""
    outs = []
    n = len(item_texts)
    for s in range(0, n, batch_size):
        chunk = {"t": item_texts[s : s + batch_size]}
        n_real = len(chunk["t"])
        padded, _ = pad_to_batch(chunk, batch_size)
        outs.append(np.asarray(_encode_items_jit(model, params, padded["t"]))[:n_real])
    return jnp.asarray(np.concatenate(outs))


def make_fusion_fn(model, item_sem_ids, n_candidates, n_beam, alpha):
    @jax.jit
    def fuse(params, batch, item_vecs):
        return beam_fusion(
            model, params, batch["input_ids"], batch["encoder_input_ids"],
            item_vecs, item_sem_ids,
            n_candidates=n_candidates, n_beam=n_beam, alpha=alpha,
        )

    return fuse


def evaluate(fusion_fn, params, arrays, item_vecs, batch_size, mesh, C):
    from genrec_tpu.parallel import metric_allreduce

    acc = TopKAccumulator(ks=(1, 5, 10))
    cb_correct = np.zeros(C)
    cb_total = 0
    # Same prefetching iterator as the train loop: host batch assembly and
    # H2D transfer overlap the previous batch's beam fusion.
    for sharded, host, valid in prefetch_eval_batches(
        batch_iterator(arrays, batch_size), mesh
    ):
        out = fusion_fn(params, sharded, item_vecs)
        n = int(valid.sum())
        topk = np.asarray(out.sem_ids)[:n]
        target = host["target_sem_ids"][:n]
        acc.accumulate(jnp.asarray(target), jnp.asarray(topk))
        top1 = topk[:, 0, :]
        for c in range(C):
            cb_correct[c] += (top1[:, c] == target[:, c]).sum()
        cb_total += n
    metrics = acc.reduce(cross_process=True)
    # Same cross-host scope as the TopK metrics.
    cb = metric_allreduce({"correct": list(cb_correct), "total": float(cb_total)})
    metrics.update(
        {f"codebook_acc_{c}": cb["correct"][c] / max(cb["total"], 1) for c in range(C)}
    )
    return metrics


@configlib.configurable
def train(
    epochs=50,
    batch_size=64,
    learning_rate=3e-4,
    num_warmup_steps=100,
    weight_decay=0.01,
    sparse_loss_weight=1.0,
    dense_loss_weight=1.0,
    encoder_n_layers=1,
    encoder_hidden_dim=768,
    encoder_num_heads=8,
    encoder_vocab_size=32128,
    id_vocab_size=512,
    n_codebooks=3,
    d_model=768,
    max_len=1024,
    infonce_temperature=0.2,
    decoder_n_layers=8,
    decoder_num_heads=6,
    decoder_dropout=0.1,
    max_items=20,
    n_beam=20,
    fusion_alpha=0.5,
    dataset="synthetic",
    dataset_folder="dataset/amazon",
    split="beauty",
    sem_ids_path=None,
    do_eval=True,
    eval_every_epoch=10,
    eval_batch_size=32,
    # False: final valid/test with final-epoch weights — the reference
    # COBRA trainer's protocol (no best tracking); True keeps the
    # best-valid-Recall@10 snapshot protocol of sasrec/hstu.
    test_on_best=True,
    save_dir_root="out/cobra",
    save_every_epoch=50,
    resume_from_checkpoint=False,
    wandb_logging=False,
    wandb_project="cobra_training",
    wandb_log_interval=100,
    amp=True,
    mixed_precision_type="bf16",
    profile_steps=0,
    seed=0,
):
    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    mesh = get_mesh()

    if callable(dataset):
        # Injected data factory returning a CobraSeqData — mirrors the
        # reference trainer's dataset-class parameter (cobra_trainer.py:99)
        # and is how the parity harness feeds shared fixed token tables.
        data = dataset()
        id_vocab_size = data.id_vocab_size
        n_codebooks = data.C
    elif dataset == "synthetic":
        data = synthetic_cobra_data(
            id_vocab_size=id_vocab_size, n_codebooks=n_codebooks,
            text_vocab=encoder_vocab_size, max_items=max_items, seed=seed,
        )
    else:
        from genrec_tpu.data.cobra_seq import amazon_cobra_data

        if sem_ids_path is None:
            raise ValueError("amazon dataset needs sem_ids_path (RQ-VAE artifact)")
        data = amazon_cobra_data(
            dataset_folder, split, sem_ids_path, max_items=max_items
        )
        id_vocab_size = data.id_vocab_size
        n_codebooks = data.C

    train_arrays = data.train_arrays()
    valid_arrays = data.eval_arrays("valid")
    test_arrays = data.eval_arrays("test")
    item_sem_ids = jnp.asarray(data.sem_ids)

    compute_dtype = jnp.bfloat16 if (amp and mixed_precision_type == "bf16") else jnp.float32
    model = Cobra(
        encoder_n_layers=encoder_n_layers,
        encoder_hidden_dim=encoder_hidden_dim,
        encoder_num_heads=encoder_num_heads,
        encoder_vocab_size=encoder_vocab_size,
        id_vocab_size=id_vocab_size,
        n_codebooks=n_codebooks,
        d_model=d_model,
        max_len=max_len,
        temperature=infonce_temperature,
        decoder_n_layers=decoder_n_layers,
        decoder_num_heads=decoder_num_heads,
        decoder_dropout=decoder_dropout,
        dtype=compute_dtype,
    )
    rng = jax.random.key(seed)
    init_rng, state_rng = jax.random.split(rng)
    params = model.init(
        init_rng,
        jnp.full((1, (max_items + 1) * n_codebooks), data.pad_id, jnp.int32),
        jnp.zeros((1, max_items + 1, data.item_texts.shape[1]), jnp.int32),
    )["params"]

    steps_per_epoch = max(1, len(train_arrays["input_ids"]) // batch_size)
    total_steps = epochs * steps_per_epoch
    schedule = cosine_schedule_with_warmup(learning_rate, num_warmup_steps, total_steps)
    optimizer = optax.adamw(schedule, weight_decay=weight_decay)

    def loss_fn(p, batch, step_rng):
        out = model.apply(
            {"params": p}, batch["input_ids"], batch["encoder_input_ids"],
            deterministic=False, rngs={"dropout": step_rng},
        )
        loss = sparse_loss_weight * out.loss_sparse + dense_loss_weight * out.loss_dense
        return loss, {
            "loss_sparse": out.loss_sparse,
            "loss_dense": out.loss_dense,
            "acc": out.acc_correct / jnp.maximum(out.acc_total, 1),
            "codebook_entropy": out.codebook_entropy,
        }

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=1.0))
    state = replicate(mesh, TrainState.create(params, optimizer, state_rng))
    # Reference eval: n_candidates=10 of n_beam=20 (cobra_trainer.py:433-435);
    # clamped so small-beam debug runs stay valid.
    fusion_fn = make_fusion_fn(
        model, item_sem_ids, min(10, n_beam), n_beam, fusion_alpha
    )

    from genrec_tpu.core.checkpoint import BestTracker, CheckpointManager, save_params
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt = CheckpointManager(os.path.join(save_dir_root, "checkpoints")) if save_dir_root else None
    best = BestTracker(save_dir_root)
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)

    def step_log(m, g):
        return {
            "global_step": g,
            "train/loss": float(m["loss"]),
            "train/loss_sparse": float(m["loss_sparse"]),
            "train/loss_dense": float(m["loss_dense"]),
            "train/acc": float(m["acc"]),
            "train/codebook_entropy": float(m["codebook_entropy"]),
        }

    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt,
        rows_per_step=batch_size,
        row_len=(max_items + 1) * n_codebooks, seed=seed,
        pack_sequences=False, train_arrays=train_arrays,
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
        step_log=step_log,
    )
    start_epoch, start_batch, global_step = 0, 0, 0
    if resume_from_checkpoint:
        # Step-granular exact resume: continues at the exact next batch
        # of a possibly mid-epoch resume point.
        state, start_epoch, start_batch, global_step = loop.resume(
            state, lambda s: replicate(mesh, s)
        )
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point (even mid-FINAL-epoch — the
            # hole the old epoch-granular guard left open); exit cleanly
            # so the scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return {}, {}

        if ckpt is not None and (epoch + 1) % save_every_epoch == 0:
            # Epoch-boundary resume point: cursor = (next epoch, batch 0).
            loop.save(state, epoch=epoch + 1, next_batch=0,
                      global_step=global_step)

        if do_eval and (epoch + 1) % eval_every_epoch == 0:
            item_vecs = compute_item_dense_vecs(model, state.params, data.item_texts)
            m = evaluate(fusion_fn, state.params, valid_arrays, item_vecs,
                         eval_batch_size, mesh, n_codebooks)
            logger.info(
                f"epoch {epoch} valid " + ", ".join(f"{k}={v:.4f}" for k, v in m.items())
            )
            tracker.log({"epoch": epoch, **{f"eval/{k}": v for k, v in m.items()}})
            best.update(m["Recall@10"], state.params)

    # Unconditional final resume point: closes the old hole where a
    # save_every_epoch cadence never firing left a completed run with
    # NOTHING on disk to resume from.
    loop.save(state, epoch=epochs, next_batch=0, global_step=global_step)
    final_params = best.best_params(like=state.params) if test_on_best else None
    if final_params is None:
        final_params = state.params
    item_vecs = compute_item_dense_vecs(model, final_params, data.item_texts)
    valid_metrics = evaluate(fusion_fn, final_params, valid_arrays, item_vecs,
                             eval_batch_size, mesh, n_codebooks)
    test_metrics = evaluate(fusion_fn, final_params, test_arrays, item_vecs,
                            eval_batch_size, mesh, n_codebooks)
    logger.info("test " + ", ".join(f"{k}={v:.4f}" for k, v in test_metrics.items()))
    tracker.log({f"test/{k}": v for k, v in test_metrics.items()})
    if save_dir_root and best.value < 0:  # no eval ran: snapshot final params
        save_params(os.path.join(save_dir_root, "best_model"), final_params)
    loop.shutdown()
    return valid_metrics, test_metrics


if __name__ == "__main__":
    configlib.parse_config()
    train()
