"""HSTU trainer (parity target: reference genrec/trainers/hstu_trainer.py).

Identical skeleton to the SASRec trainer (epoch loop, Adam(b2=0.98), no LR
schedule, full-vocab eval) plus timestamp pass-through (hstu_trainer.py:152-157).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import (
    batch_iterator,
    fold_valid,
    pack_examples,
    prefetch_to_device,
)
from genrec_tpu.data.synthetic import SyntheticSeqDataset
from genrec_tpu.models.hstu import HSTU
from genrec_tpu.ops.metrics import first_match_ranks
from genrec_tpu.parallel import distributed_init, get_mesh, metric_allreduce, replicate


def make_eval_step(model):
    @jax.jit
    def eval_step(params, batch, valid):
        logits, _ = model.apply(
            {"params": params}, batch["input_ids"], batch.get("timestamps")
        )
        last = logits[:, -1, :].astype(jnp.float32).at[:, 0].set(-jnp.inf)
        _, top = jax.lax.top_k(last, 10)
        ranks = first_match_ranks(batch["targets"], top[..., None])
        v = valid.astype(jnp.float32)
        out = {"total": v.sum()}
        for k in (1, 5, 10):
            out[f"recall_sum@{k}"] = jnp.sum((ranks < k) * v)
            out[f"ndcg_sum@{k}"] = jnp.sum(
                jnp.where(ranks < k, 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0), 0.0) * v
            )
        return out

    return eval_step


def evaluate(eval_step, params, arrays, batch_size, mesh):
    sums: dict[str, float] = {}
    # Prefetching iterator (valid mask folded in): eval overlaps H2D
    # transfer with compute like training.
    for sharded, _ in prefetch_to_device(
        fold_valid(batch_iterator(arrays, batch_size)), mesh
    ):
        got = eval_step(params, sharded, sharded["valid"])
        for k, v in got.items():
            sums[k] = sums.get(k, 0.0) + float(v)
    sums = metric_allreduce(sums)
    total = max(sums.get("total", 0.0), 1.0)
    return {
        **{f"Recall@{k}": sums[f"recall_sum@{k}"] / total for k in (1, 5, 10)},
        **{f"NDCG@{k}": sums[f"ndcg_sum@{k}"] / total for k in (1, 5, 10)},
    }


@configlib.configurable
def train(
    epochs=10,
    batch_size=128,
    learning_rate=1e-3,
    weight_decay=0.0,
    max_seq_len=50,
    embed_dim=64,
    num_heads=2,
    num_blocks=2,
    dropout=0.2,
    num_position_buckets=32,
    num_time_buckets=64,
    max_position_distance=128,
    use_temporal_bias=True,
    use_pallas="auto",
    # Fused full-softmax CE over the tied item-embedding head
    # (kernels/fused_ce.py): same loss, no (B,L,V) logits in HBM.
    # auto = on when running on TPU (Mosaic-compiled only).
    use_fused_ce="auto",
    # First-fit-decreasing sequence packing: segment-aware attention keeps
    # multiple short histories per row (temporal/positional buckets never
    # bridge segments — the Pallas kernel masks cross-segment pairs
    # in-register). HSTU's biases are relative-only, so eval stays on the
    # original left-padded rows. False restores the unpacked layout.
    pack_sequences=True,
    dataset="synthetic",
    dataset_folder="dataset/amazon",
    split="beauty",
    num_items=None,
    do_eval=True,
    eval_every_epoch=1,
    eval_batch_size=256,
    save_dir_root="out/hstu",
    save_every_epoch=50,
    resume_from_checkpoint=False,
    wandb_logging=False,
    wandb_project="hstu_training",
    wandb_log_interval=100,
    amp=True,
    mixed_precision_type="bf16",
    profile_steps=0,
    seed=0,
):
    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    mesh = get_mesh()

    if dataset == "synthetic":
        ds = SyntheticSeqDataset(max_seq_len=max_seq_len, seed=seed)
        n_items = num_items or ds.num_items
        valid_arrays = ds.eval_arrays_with_time("valid")
        test_arrays = ds.eval_arrays_with_time("test")
        train_examples = lambda: ds.train_examples(with_time=True)
        padded_train = ds.train_arrays_with_time
    else:
        from genrec_tpu.data.amazon import AmazonSASRecData

        ds = AmazonSASRecData(
            root=dataset_folder, split=split, max_seq_len=max_seq_len,
            with_timestamps=True,
        )
        n_items = ds.num_items
        valid_arrays = ds.eval_arrays("valid")
        test_arrays = ds.eval_arrays("test")
        train_examples = ds.train_examples
        padded_train = ds.train_arrays

    repack, train_arrays = None, None
    if pack_sequences:
        # Raw examples only — never materialize the padded train matrix
        # just to discard it for the packed layout. Re-packed per epoch
        # (epoch-seeded shuffle) so example co-location is re-mixed like
        # the padded layout's per-epoch permutation; PackedTrainLoop
        # calls this lazily per epoch.
        examples = train_examples()

        def repack(epoch: int):
            arrays, rep = pack_examples(
                examples, row_len=max_seq_len, seed=(seed, epoch)
            )
            # HSTU has no absolute positions and segment_valid has no
            # consumer in its token-level CE — don't ship them to device.
            arrays.pop("positions")
            arrays.pop("segment_valid")
            return arrays, rep

    else:
        train_arrays = padded_train()

    compute_dtype = jnp.bfloat16 if (amp and mixed_precision_type == "bf16") else jnp.float32
    if use_pallas == "auto":
        from genrec_tpu.kernels.policy import auto_pallas_attention

        use_pallas = auto_pallas_attention()
    if use_fused_ce == "auto":
        from genrec_tpu.kernels.policy import auto_fused_ce

        use_fused_ce = auto_fused_ce()
    model = HSTU(
        num_items=n_items,
        max_seq_len=max_seq_len,
        embed_dim=embed_dim,
        num_heads=num_heads,
        num_blocks=num_blocks,
        dropout=dropout,
        num_position_buckets=num_position_buckets,
        num_time_buckets=num_time_buckets,
        max_position_distance=max_position_distance,
        use_temporal_bias=use_temporal_bias,
        use_pallas=bool(use_pallas),
        fused_ce=bool(use_fused_ce),
        dtype=compute_dtype,
    )
    rng = jax.random.key(seed)
    init_rng, state_rng = jax.random.split(rng)
    params = model.init(
        init_rng, jnp.zeros((1, max_seq_len), jnp.int32),
        jnp.zeros((1, max_seq_len), jnp.int32),
    )["params"]

    optimizer = (
        optax.adamw(learning_rate, b2=0.98, weight_decay=weight_decay)
        if weight_decay
        else optax.adam(learning_rate, b2=0.98)
    )

    def loss_fn(p, batch, step_rng):
        _, loss = model.apply(
            {"params": p}, batch["input_ids"], batch.get("timestamps"),
            batch["targets"], deterministic=False,
            segment_ids=batch.get("segment_ids"), rngs={"dropout": step_rng},
        )
        aux = {}
        if "segment_ids" in batch:
            aux["real_tokens"] = jnp.sum(batch["segment_ids"] != 0).astype(jnp.float32)
        return loss, aux

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=None))
    state = replicate(mesh, TrainState.create(params, optimizer, state_rng))
    eval_step = make_eval_step(model)

    from genrec_tpu.core.checkpoint import BestTracker, CheckpointManager, save_params
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt = CheckpointManager(os.path.join(save_dir_root, "checkpoints")) if save_dir_root else None
    best = BestTracker(save_dir_root)
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)
    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt,
        rows_per_step=batch_size, row_len=max_seq_len, seed=seed,
        pack_sequences=pack_sequences, repack=repack, train_arrays=train_arrays,
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
    )
    start_epoch, start_batch, global_step = 0, 0, 0
    if resume_from_checkpoint:
        # Step-granular exact resume through the integrity ladder.
        state, start_epoch, start_batch, global_step = loop.resume(
            state, lambda s: replicate(mesh, s)
        )
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point; exit cleanly so the
            # scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return {}, {}

        if ckpt is not None and (epoch + 1) % save_every_epoch == 0:
            # Epoch-boundary resume point: cursor = (next epoch, batch 0).
            loop.save(state, epoch=epoch + 1, next_batch=0, global_step=global_step)

        if do_eval and (epoch + 1) % eval_every_epoch == 0:
            m = evaluate(eval_step, state.params, valid_arrays, eval_batch_size, mesh)
            logger.info(
                f"epoch {epoch} valid " + ", ".join(f"{k}={v:.4f}" for k, v in m.items())
            )
            tracker.log({"epoch": epoch, **{f"eval/{k}": v for k, v in m.items()}})
            best.update(m["Recall@10"], state.params)

    final_params = best.best_params(like=state.params)
    if final_params is None:
        final_params = state.params
    valid_metrics = evaluate(eval_step, final_params, valid_arrays, eval_batch_size, mesh)
    test_metrics = evaluate(eval_step, final_params, test_arrays, eval_batch_size, mesh)
    logger.info("test " + ", ".join(f"{k}={v:.4f}" for k, v in test_metrics.items()))
    tracker.log({f"test/{k}": v for k, v in test_metrics.items()})
    if save_dir_root and best.value < 0:  # no eval ran: snapshot final params
        save_params(os.path.join(save_dir_root, "best_model"), final_params)
    loop.shutdown()
    return valid_metrics, test_metrics


if __name__ == "__main__":
    configlib.parse_config()
    train()
