"""Streaming trainer: tail the interaction log, repack incrementally,
commit + publish on a cadence — the training half of the continuous
pipeline (docs/training.md "Streaming training").

The driver owns NO new training machinery: it maps the append-only
`data.stream_log` onto `PackedTrainLoop`'s existing epoch/cursor
contract and lets the loop's step-granular fault tolerance do the rest.

- **Chunk-as-epoch**: fixed-size chunks of ``chunk_records`` consecutive
  records; chunk *k* IS epoch *k*. ``make_arrays(payloads, epoch)``
  turns a chunk into the loop's static-shape arrays deterministically,
  so the loop's ``{epoch, next_batch, data_seed}`` resume point names an
  exact position in the RECORD stream: a trainer killed anywhere (by
  SIGTERM, SIGKILL mid-commit, or SIGKILL mid-publish) resumes at the
  exact record with per-step loss parity (tests/test_pipeline.py).
- **Commit cadence**: every ``commit_every_steps`` optimizer steps (and
  at every chunk boundary) a durable resume point goes through the
  existing `CheckpointManager` coordinated-commit path. The log cursor
  (`stream_log.CursorStore`) commits beside it, carrying the SAME
  ``{epoch, next_batch, global_step, data_seed}`` coordinates, so log
  position and train position can never disagree by more than one
  in-flight commit.
- **Publish**: on its own cadence the bare ``state.params`` tree is
  saved to a SEPARATE publish directory (its own `CheckpointManager`,
  same coordinated-commit marker), which is the only directory serving
  ever watches — a torn publish (SIGKILL in flight,
  ``ChaosPlan.die_in_publish_at_step``) has no commit marker and is
  quarantined on the next trainer start, invisible to the rollout guard
  (serving/rollout.py) forever.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

from genrec_tpu.core import chaos
from genrec_tpu.core.checkpoint import _COMMIT_MARKER, CheckpointManager
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.preemption import PreemptionGuard
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.data.stream_log import CursorStore, StreamLogReader
from genrec_tpu.trainers.packed_loop import PackedTrainLoop


class _ChunkReport:
    """PackingReport stand-in for one log chunk (the loop only reads
    ``n_examples``/``n_rows`` for its rate math)."""

    def __init__(self, n_examples: int, n_rows: int, epoch: int):
        self.n_examples = n_examples
        self.n_rows = n_rows
        self.epoch = epoch

    def __str__(self) -> str:
        return (f"stream chunk {self.epoch}: {self.n_examples} records "
                f"packed into {self.n_rows} rows")


class StreamTrainer:
    """Drives one model's incremental training off an interaction log.

    ``make_arrays(payloads, epoch) -> dict[str, np.ndarray]`` must be
    DETERMINISTIC in its inputs (any shuffling keyed off ``epoch``): the
    exactness of crash resume rests on chunk *k* repacking to identical
    arrays on every attempt. ``step_fn(state, batch) -> (state,
    metrics)`` is any jitted step whose metrics carry ``"loss"``.
    """

    def __init__(
        self,
        *,
        log_dir: str,
        save_dir_root: str,
        state,
        step_fn: Callable,
        make_arrays: Callable[[list, int], dict],
        chunk_records: int,
        rows_per_step: int,
        row_len: int = 1,
        seed: int = 0,
        publish_dir: str | None = None,
        commit_every_steps: int = 0,
        publish_every_steps: int = 0,
        publish_params: Callable[[Any], Any] | None = None,
        max_to_keep: int = 5,
        logger=None,
        guard: PreemptionGuard | None = None,
        handle_signals: bool = True,
        wandb_log_interval: int = 1,
    ):
        if chunk_records % rows_per_step:
            raise ValueError(
                f"chunk_records={chunk_records} must be a multiple of "
                f"rows_per_step={rows_per_step} (drop_last would strand "
                "records at every chunk tail)"
            )
        from genrec_tpu.parallel import get_mesh, replicate

        self.log_dir = log_dir
        self.save_dir_root = save_dir_root
        self.publish_dir = publish_dir
        self.chunk_records = int(chunk_records)
        self.commit_every_steps = int(commit_every_steps)
        self.publish_every_steps = int(publish_every_steps)
        self.publish_params = publish_params or (lambda s: s.params)
        self.step_fn = step_fn
        self.make_arrays = make_arrays
        self.reader = StreamLogReader(log_dir)
        self.cursor = CursorStore(os.path.join(save_dir_root, "stream_cursor.json"))
        self.logger = logger or setup_logger(save_dir_root)
        self.tracker = Tracker(save_dir=save_dir_root)
        self.mesh = get_mesh()
        self.state_like = replicate(self.mesh, state)
        self.ckpt = CheckpointManager(
            os.path.join(save_dir_root, "checkpoints"), max_to_keep=max_to_keep
        )
        self._publish_mgr = (
            CheckpointManager(publish_dir, max_to_keep=max_to_keep)
            if publish_dir else None
        )
        self.published_steps: list[int] = []
        if self._publish_mgr is not None:
            self._quarantine_torn_publishes()
        self.guard = guard if guard is not None else (
            PreemptionGuard() if handle_signals else None
        )
        self.loop = PackedTrainLoop(
            logger=self.logger, tracker=self.tracker,
            prof=ProfileWindow("", 0), mesh=self.mesh, guard=self.guard,
            ckpt=self.ckpt, rows_per_step=rows_per_step, row_len=row_len,
            seed=seed, pack_sequences=True, repack=self._repack,
            wandb_log_interval=wandb_log_interval,
            save_dir_root=save_dir_root,
            step_hook=self._step_hook if commit_every_steps else None,
        )

    # -- log → arrays -------------------------------------------------------

    def _repack(self, epoch: int):
        start = epoch * self.chunk_records
        payloads = self.reader.read(start, self.chunk_records)
        if len(payloads) < self.chunk_records:
            raise RuntimeError(
                f"chunk {epoch} not fully committed: wanted "
                f"{self.chunk_records} records from {start}, log has "
                f"{len(payloads)} (run() waits before repacking)"
            )
        arrays = self.make_arrays(payloads, epoch)
        n_rows = len(next(iter(arrays.values())))
        return arrays, _ChunkReport(self.chunk_records, n_rows, epoch)

    # -- commit + publish ---------------------------------------------------

    def _commit(self, state, epoch: int, next_batch: int, global_step: int,
                wait: bool = False) -> None:
        """One coordinated commit: resume point through the checkpoint
        manager, then the log cursor with the SAME coordinates. The
        cursor's ``record`` is the stream position every record BEFORE
        which is fully consumed (chunk granularity; the meta names the
        exact mid-chunk batch)."""
        self.loop.save(state, epoch=epoch, next_batch=next_batch,
                       global_step=global_step, wait=wait)
        self.cursor.save(epoch * self.chunk_records, meta={
            "epoch": epoch, "next_batch": next_batch,
            "global_step": global_step, "data_seed": self.loop.seed,
        })

    def _quarantine_torn_publishes(self) -> None:
        """A publish SIGKILL'd in flight leaves a marker-less step dir
        that would collide with the re-publish after resume: quarantine
        it (same discipline the restore ladder applies on read)."""
        for name in os.listdir(self.publish_dir):
            if not name.isdigit():
                continue
            if not os.path.exists(
                os.path.join(self.publish_dir, name, _COMMIT_MARKER)
            ):
                self.logger.warning(
                    f"stream trainer: quarantining torn publish step {name}"
                )
                self._publish_mgr.quarantine(int(name))

    def _publish(self, state, global_step: int) -> None:
        if self._publish_mgr is None:
            return
        latest = self._publish_mgr.latest_step()
        if latest is not None and global_step <= latest:
            # Already durably published (a crash after publish but before
            # the NEXT commit replays this step on resume): exact resume
            # makes the params identical, so skipping is correct.
            return
        self._publish_mgr.save(global_step, self.publish_params(state))
        # Chaos: a SIGKILL here leaves the publish write in flight — the
        # step must never gain a commit marker.
        chaos.maybe_die_in_publish(global_step)
        self._publish_mgr.wait()
        self.published_steps.append(global_step)
        self.logger.info(f"stream trainer: published params step {global_step}")

    def _step_hook(self, state, epoch: int, consumed: int, global_step: int):
        if self.commit_every_steps and global_step % self.commit_every_steps == 0:
            self._commit(state, epoch, consumed, global_step)
        if self.publish_every_steps and global_step % self.publish_every_steps == 0:
            self._publish(state, global_step)

    # -- the tail loop ------------------------------------------------------

    def run(self, *, max_chunks: int | None = None, poll_secs: float = 0.05,
            idle_timeout_s: float | None = 5.0) -> dict:
        """Tail the log until ``max_chunks`` chunks are trained (or the
        log stops growing for ``idle_timeout_s``). Returns a summary;
        ``preempted=True`` means a durable resume point was written and
        a rerun continues exactly where this one stopped."""
        state, epoch, start_batch, global_step = self.loop.resume(self.state_like)
        preempted = False
        chunks_done = 0
        idle_since = None
        try:
            while max_chunks is None or epoch < max_chunks:
                need = (epoch + 1) * self.chunk_records
                if self.reader.count() < need:
                    if self.loop.fleet_preempted():
                        self._commit(state, epoch, start_batch, global_step,
                                     wait=True)
                        preempted = True
                        break
                    idle_since = idle_since or time.monotonic()
                    if (idle_timeout_s is not None
                            and time.monotonic() - idle_since > idle_timeout_s):
                        break
                    time.sleep(poll_secs)
                    continue
                idle_since = None
                res = self.loop.run_epoch(
                    state, self.step_fn, epoch, global_step,
                    start_batch=start_batch,
                )
                state, global_step = res.state, res.global_step
                if res.preempted:
                    preempted = True
                    break
                chunks_done += 1
                epoch += 1
                start_batch = 0
                # Chunk-boundary commit + publish regardless of cadence:
                # the boundary is where the cursor is simplest (next
                # chunk, batch 0) and where freshness is accounted.
                self._commit(state, epoch, 0, global_step)
                self._publish(state, global_step)
        finally:
            self.loop.shutdown(preempted_epoch=epoch if preempted else None)
            if self._publish_mgr is not None:
                self._publish_mgr.close()
        return {
            "global_step": global_step,
            "epoch": epoch,
            "chunks_done": chunks_done,
            "records_consumed": epoch * self.chunk_records,
            "preempted": preempted,
            "published_steps": list(self.published_steps),
        }
