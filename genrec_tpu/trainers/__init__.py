"""Trainers: one gin-configurable `train()` per model family.

Layout mirrors the reference (genrec/trainers/__init__.py:1-25): each
trainer is a self-contained script invoked as
``python -m genrec_tpu.trainers.<x>_trainer <config.gin> [--split ...]``,
but the loop body is a single jitted SPMD step from core.harness instead
of an Accelerate-wrapped torch loop.
"""
