"""NoteLLM (Query2Embedding) trainer — BEYOND the reference.

The reference ships NoteLLM as library code only ("no trainer or config
in-repo", genrec/models/notellm.py; SURVEY.md §2.1); this trainer makes
the family trainable end to end: paired contrastive SFT over interleaved
(query, positive) batches with the learnable temperature tau trained
jointly with the backbone, evaluated as paired top-k retrieval accuracy
(reference compute_metrics, notellm.py:236-265) on held-out topics.

Loop shape mirrors every other trainer here: one jitted SPMD step
(core/harness.make_train_step), data-parallel mesh, host-prefetched
batches, orbax checkpoints with auto-resume, BestTracker on the
retrieval metric, JSONL/wandb logging, per-epoch seq/s/chip.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import batch_iterator, prefetch_to_device
from genrec_tpu.data.notellm_pairs import NoteLLMPairData
from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.notellm import paired_topk_accuracy, query2embedding_forward
from genrec_tpu.ops.schedules import cosine_schedule_with_warmup
from genrec_tpu.parallel import distributed_init, get_mesh, to_host


def _flatten_pairs(batch):
    """(B, 2, ...) pair-unit arrays -> (2B, ...) interleaved rows."""
    return {
        k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()
    }


def make_embed_fn(model):
    @jax.jit
    def embed(params, batch):
        out = query2embedding_forward(
            model, params["backbone"], batch["input_ids"],
            batch["attention_mask"], batch["emb_idx"], params["tau"],
            return_loss=False,
        )
        return out.sentence_embedding

    return embed


def evaluate_retrieval(embed_fn, params, arrays, batch_pairs, mesh, topk=5):
    """Paired top-k accuracy over the full eval set (embeddings gathered
    on host; the sim matrix spans every eval pair, not one batch)."""
    embs = []
    # Prefetching iterator: H2D transfer overlaps the embed compute.
    for sharded, valid in prefetch_to_device(batch_iterator(arrays, batch_pairs), mesh):
        e = to_host(embed_fn(params, _flatten_pairs(sharded)))
        n = int(valid.sum())
        embs.append(e.reshape(-1, 2, e.shape[-1])[:n])
    flat = jnp.asarray(np.concatenate(embs).reshape(-1, embs[0].shape[-1]))
    return {f"top{topk}_acc": paired_topk_accuracy(flat, topk=topk)}


@configlib.configurable
def train(
    epochs=4,
    batch_pairs=16,
    learning_rate=1e-3,
    num_warmup_steps=20,
    weight_decay=0.01,
    max_text_len=12,
    num_topics=64,
    eval_topics=16,
    pairs_per_topic=4,
    hidden_size=64,
    intermediate_size=128,
    n_layers=2,
    num_heads=4,
    num_kv_heads=2,
    tau_init=3.0,
    eval_topk=5,
    do_eval=True,
    eval_every_epoch=2,
    eval_batch_pairs=16,
    resume_from_checkpoint=False,
    save_dir_root="out/notellm",
    save_every_epoch=10,
    wandb_logging=False,
    wandb_project="notellm_training",
    wandb_log_interval=50,
    amp=True,
    mixed_precision_type="bf16",
    profile_steps=0,
    seed=0,
):
    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    mesh = get_mesh()
    compute_dtype = (
        jnp.bfloat16 if (amp and mixed_precision_type == "bf16") else jnp.float32
    )

    rng = jax.random.key(seed)
    init_rng, state_rng = jax.random.split(rng)

    data = NoteLLMPairData(
        num_topics=num_topics, eval_topics=eval_topics,
        max_len=max_text_len, seed=seed,
    )
    cfg = QwenConfig(
        vocab_size=data.tokenizer.vocab_size, hidden_size=hidden_size,
        intermediate_size=intermediate_size, num_hidden_layers=n_layers,
        num_attention_heads=num_heads, num_key_value_heads=num_kv_heads,
        max_position_embeddings=max_text_len, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = QwenLM(cfg, dtype=compute_dtype)
    backbone = model.init(init_rng, jnp.zeros((1, 4), jnp.int32))["params"]
    # tau is trained jointly (reference notellm.py:170: learnable
    # temperature, exp'd in the loss).
    params = {"backbone": backbone, "tau": jnp.asarray(tau_init, jnp.float32)}
    logger.info(
        f"NoteLLM backbone {hidden_size}d x {n_layers} layers, "
        f"vocab {cfg.vocab_size}, tau_init {tau_init}"
    )

    train_arrays = data.train_arrays(pairs_per_topic)
    eval_arrays = data.eval_arrays()
    steps_per_epoch = max(1, len(train_arrays["input_ids"]) // batch_pairs)
    schedule = cosine_schedule_with_warmup(
        learning_rate, num_warmup_steps, epochs * steps_per_epoch
    )
    # Decay only matrix-shaped weights: tau is a plain learnable scalar
    # (reference notellm.py:170 — no decay; CLIP-style practice excludes
    # the logit scale) and norm vectors are conventionally undecayed too.
    optimizer = optax.adamw(
        schedule, weight_decay=weight_decay,
        mask=lambda p: jax.tree_util.tree_map(lambda x: jnp.ndim(x) >= 2, p),
    )

    def loss_fn(p, batch, step_rng):
        flat = _flatten_pairs(batch)
        out = query2embedding_forward(
            model, p["backbone"], flat["input_ids"], flat["attention_mask"],
            flat["emb_idx"], p["tau"],
            pair_groups=batch["topic_id"],
        )
        return out.loss, {"cl_loss": out.cl_loss}

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=1.0))
    from genrec_tpu.parallel import replicate

    state = replicate(mesh, TrainState.create(params, optimizer, state_rng))
    embed_fn = make_embed_fn(model)

    from genrec_tpu.core.checkpoint import BestTracker, CheckpointManager
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt = (
        CheckpointManager(os.path.join(save_dir_root, "checkpoints"))
        if save_dir_root
        else None
    )
    best = BestTracker(save_dir_root, metric=f"top{eval_topk}_acc")
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)
    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt,
        rows_per_step=batch_pairs, row_len=max_text_len, seed=seed,
        pack_sequences=False, train_arrays=train_arrays,
        # 2 rows per pair-unit row: seq/s counts sequences, like every
        # other trainer.
        examples_per_row=2.0,
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
    )
    start_epoch, start_batch, global_step = 0, 0, 0
    if resume_from_checkpoint:
        # Step-granular exact resume: continues at the exact next batch
        # of a possibly mid-epoch resume point.
        state, start_epoch, start_batch, global_step = loop.resume(
            state, lambda s: replicate(mesh, s)
        )
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point; exit cleanly so the
            # scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return {}

        if ckpt is not None and (epoch + 1) % save_every_epoch == 0:
            # Epoch-boundary resume point: cursor = (next epoch, batch 0).
            loop.save(state, epoch=epoch + 1, next_batch=0,
                      global_step=global_step)

        if do_eval and (epoch + 1) % eval_every_epoch == 0:
            m = evaluate_retrieval(
                embed_fn, state.params, eval_arrays, eval_batch_pairs, mesh,
                topk=eval_topk,
            )
            logger.info(
                f"epoch {epoch} eval "
                + ", ".join(f"{k}={v:.4f}" for k, v in m.items())
            )
            tracker.log({"epoch": epoch, **{f"eval/{k}": v for k, v in m.items()}})
            best.update(m[f"top{eval_topk}_acc"], state.params)

    final_params = best.best_params(like=state.params) or state.params
    test_m = evaluate_retrieval(
        embed_fn, final_params, eval_arrays, eval_batch_pairs, mesh, topk=eval_topk
    )
    logger.info("test " + ", ".join(f"{k}={v:.4f}" for k, v in test_m.items()))
    tracker.log({f"test/{k}": v for k, v in test_m.items()})
    # Unconditional final resume point: the trained state is durable even
    # off the save_every_epoch cadence.
    loop.save(state, epoch=epochs, next_batch=0, global_step=global_step)
    loop.shutdown()
    return test_m


if __name__ == "__main__":
    configlib.parse_config()
    train()
