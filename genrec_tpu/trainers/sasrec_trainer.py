"""SASRec trainer (parity target: reference genrec/trainers/sasrec_trainer.py).

Loop shape matches the reference (epoch loop, Adam(b2=0.98), no LR
schedule, full-vocab eval every epoch, best-Recall@10 snapshot) but the
step is one compiled SPMD program over the data mesh and eval ranks stay
on device (no per-sample Python loops — sasrec_trainer.py:63-72 replaced
by `ops.batch_metrics`).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import (
    batch_iterator,
    fold_valid,
    pack_examples,
    prefetch_to_device,
    right_align,
)
from genrec_tpu.data.synthetic import SyntheticSeqDataset
from genrec_tpu.models.sasrec import SASRec
from genrec_tpu.ops.metrics import first_match_ranks
from genrec_tpu.parallel import distributed_init, get_mesh, metric_allreduce, replicate


def make_eval_step(model, last_from_length: bool = False):
    @jax.jit
    def eval_step(params, batch, valid):
        logits, _ = model.apply({"params": params}, batch["input_ids"])
        if last_from_length:
            # Right-padded eval rows (packed training's position indexing):
            # the prediction sits at the last VALID slot, not slot -1.
            idx = jnp.maximum(jnp.sum(batch["input_ids"] != 0, axis=1) - 1, 0)
            last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
        else:
            last = logits[:, -1, :]
        last = last.at[:, 0].set(-jnp.inf)
        _, top = jax.lax.top_k(last, 10)
        # Padded rows (valid=0) are masked out of every sum.
        ranks = first_match_ranks(batch["targets"], top[..., None])
        v = valid.astype(jnp.float32)
        out = {"total": v.sum()}
        for k in (1, 5, 10):
            out[f"recall_sum@{k}"] = jnp.sum((ranks < k) * v)
            out[f"ndcg_sum@{k}"] = jnp.sum(
                jnp.where(ranks < k, 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0), 0.0)
                * v
            )
        return out

    return eval_step


def evaluate(eval_step, params, arrays, batch_size, mesh) -> dict[str, float]:
    sums: dict[str, float] = {}
    # Prefetching iterator (valid mask folded in): eval overlaps H2D
    # transfer with compute like training.
    for sharded, _ in prefetch_to_device(
        fold_valid(batch_iterator(arrays, batch_size)), mesh
    ):
        got = eval_step(params, sharded, sharded["valid"])
        for k, v in got.items():
            sums[k] = sums.get(k, 0.0) + float(v)
    sums = metric_allreduce(sums)
    total = max(sums.get("total", 0.0), 1.0)
    out = {}
    for k in (1, 5, 10):
        out[f"Recall@{k}"] = sums[f"recall_sum@{k}"] / total
        out[f"NDCG@{k}"] = sums[f"ndcg_sum@{k}"] / total
    return out


@configlib.configurable
def train(
    epochs=10,
    batch_size=128,
    learning_rate=1e-3,
    weight_decay=0.0,
    max_seq_len=50,
    embed_dim=64,
    num_heads=2,
    num_blocks=2,
    ffn_dim=256,
    dropout=0.2,
    dataset="synthetic",
    dataset_folder="dataset/amazon",
    split="beauty",
    num_items=None,
    do_eval=True,
    eval_every_epoch=1,
    eval_batch_size=256,
    save_dir_root="out/sasrec",
    save_every_epoch=50,
    resume_from_checkpoint=False,
    wandb_logging=False,
    wandb_project="sasrec_training",
    wandb_log_interval=100,
    amp=True,
    mixed_precision_type="bf16",
    # Fused full-softmax CE over the tied item-embedding head
    # (kernels/fused_ce.py): same loss, no (B,L,V) logits in HBM.
    # auto = on when running on TPU (Mosaic-compiled only).
    use_fused_ce="auto",
    # First-fit-decreasing sequence packing (data/batching.pack_examples):
    # multiple short histories share one max_seq_len row with segment-aware
    # attention and within-segment positions, so the MXU stops paying for
    # padding. False restores the original one-example-per-row layout
    # (left-padded, absolute positions) exactly.
    pack_sequences=True,
    profile_steps=0,
    seed=0,
):
    """Returns final (valid_metrics, test_metrics) for programmatic use."""
    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    mesh = get_mesh()

    if dataset == "synthetic":
        ds = SyntheticSeqDataset(max_seq_len=max_seq_len, seed=seed)
        n_items = num_items or ds.num_items
    else:
        from genrec_tpu.data.amazon import AmazonSASRecData

        ds = AmazonSASRecData(root=dataset_folder, split=split, max_seq_len=max_seq_len)
        n_items = ds.num_items
    valid_arrays = ds.eval_arrays("valid")
    test_arrays = ds.eval_arrays("test")

    repack, train_arrays = None, None
    if pack_sequences:
        # The packer owns layout: raw examples only — never materialize
        # the padded (N, max_seq_len) train matrix just to discard it.
        # Re-packed per epoch (epoch-seeded example shuffle) so example
        # co-location in a row is re-mixed like the padded layout's
        # per-epoch permutation, not frozen at startup. PackedTrainLoop
        # calls this lazily per epoch.
        train_examples = ds.train_examples()

        def repack(epoch: int):
            arrays, rep = pack_examples(
                train_examples, row_len=max_seq_len, seed=(seed, epoch)
            )
            arrays.pop("segment_valid")  # unused by SASRec's token-level CE
            return arrays, rep

        # Eval rows must index positions the way packed training does
        # (token t at position t), and predictions come from the last
        # VALID slot (make_eval_step(last_from_length=True)).
        valid_arrays = right_align(valid_arrays)
        test_arrays = right_align(test_arrays)
    else:
        train_arrays = ds.train_arrays()

    compute_dtype = (
        jnp.bfloat16 if (amp and mixed_precision_type == "bf16") else jnp.float32
    )
    if use_fused_ce == "auto":
        from genrec_tpu.kernels.policy import auto_fused_ce

        use_fused_ce = auto_fused_ce()
    model = SASRec(
        num_items=n_items,
        max_seq_len=max_seq_len,
        embed_dim=embed_dim,
        num_heads=num_heads,
        num_blocks=num_blocks,
        ffn_dim=ffn_dim,
        dropout=dropout,
        fused_ce=bool(use_fused_ce),
        dtype=compute_dtype,
    )
    rng = jax.random.key(seed)
    init_rng, state_rng = jax.random.split(rng)
    params = model.init(
        init_rng, jnp.zeros((1, max_seq_len), jnp.int32), deterministic=True
    )["params"]

    # Reference uses Adam with beta2=0.98 and no schedule.
    optimizer = (
        optax.adamw(learning_rate, b2=0.98, weight_decay=weight_decay)
        if weight_decay
        else optax.adam(learning_rate, b2=0.98)
    )

    def loss_fn(params, batch, step_rng):
        _, loss = model.apply(
            {"params": params},
            batch["input_ids"],
            batch["targets"],
            deterministic=False,
            segment_ids=batch.get("segment_ids"),
            positions=batch.get("positions"),
            rngs={"dropout": step_rng},
        )
        aux = {}
        if "segment_ids" in batch:
            # tokens-per-step / occupancy surface in the step metrics.
            aux["real_tokens"] = jnp.sum(batch["segment_ids"] != 0).astype(jnp.float32)
        return loss, aux

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=None))
    state = replicate(mesh, TrainState.create(params, optimizer, state_rng))
    # One jit cache for every eval call; packed training reads predictions
    # from the last valid slot of right-padded eval rows.
    eval_step = make_eval_step(model, last_from_length=pack_sequences)

    from genrec_tpu.core.checkpoint import BestTracker, CheckpointManager, save_params
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt_mgr = CheckpointManager(os.path.join(save_dir_root, "checkpoints")) if save_dir_root else None
    best = BestTracker(save_dir_root)
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)
    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt_mgr,
        rows_per_step=batch_size, row_len=max_seq_len, seed=seed,
        pack_sequences=pack_sequences, repack=repack, train_arrays=train_arrays,
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
    )
    start_epoch, start_batch, global_step = 0, 0, 0
    if resume_from_checkpoint:
        # Step-granular exact resume: restores TrainState + the data
        # cursor through the integrity ladder, continuing at the exact
        # next batch of a possibly mid-epoch resume point.
        state, start_epoch, start_batch, global_step = loop.resume(
            state, lambda s: replicate(mesh, s)
        )
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point; exit cleanly so the
            # scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return {}, {}

        if ckpt_mgr is not None and (epoch + 1) % save_every_epoch == 0:
            # Epoch-boundary resume point: cursor = (next epoch, batch 0).
            loop.save(state, epoch=epoch + 1, next_batch=0, global_step=global_step)

        if do_eval and (epoch + 1) % eval_every_epoch == 0:
            m = evaluate(eval_step, state.params, valid_arrays, eval_batch_size, mesh)
            logger.info(
                f"epoch {epoch} valid " + ", ".join(f"{k}={v:.4f}" for k, v in m.items())
            )
            tracker.log({"epoch": epoch, **{f"eval/{k}": v for k, v in m.items()}})
            best.update(m["Recall@10"], state.params)

    final_params = best.best_params(like=state.params)
    if final_params is None:
        final_params = state.params
    valid_metrics = evaluate(eval_step, final_params, valid_arrays, eval_batch_size, mesh)
    test_metrics = evaluate(eval_step, final_params, test_arrays, eval_batch_size, mesh)
    logger.info("test " + ", ".join(f"{k}={v:.4f}" for k, v in test_metrics.items()))
    tracker.log({f"test/{k}": v for k, v in test_metrics.items()})

    if save_dir_root and best.value < 0:  # no eval ran: snapshot final params
        save_params(os.path.join(save_dir_root, "best_model"), final_params)
    loop.shutdown()
    return valid_metrics, test_metrics


# ---------------------------------------------------------------------------
# graftlint compile manifest (scripts/graftlint.py, docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

from genrec_tpu.analysis.manifest import BuiltEntry, register_entry


@register_entry("train/sasrec_packed_step", tags=("train", "packed"))
def _graftlint_entry() -> BuiltEntry:
    """CI-shape replica of this trainer's jitted step, SAME jit config as
    train() above (make_train_step flags, donate_argnums=0): the IR rules
    audit what production compiles, at sizes a CPU lowers in seconds."""
    import numpy as np

    model = SASRec(num_items=50, max_seq_len=16, embed_dim=16, num_heads=2,
                   num_blocks=1, ffn_dim=32, dropout=0.0)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 16), jnp.int32), deterministic=True
    )["params"]
    optimizer = optax.adam(1e-3, b2=0.98)

    def loss_fn(p, batch, step_rng):
        _, loss = model.apply(
            {"params": p}, batch["input_ids"], batch["targets"],
            deterministic=False, segment_ids=batch["segment_ids"],
            positions=batch["positions"], rngs={"dropout": step_rng},
        )
        return loss, {"real_tokens": jnp.sum(batch["segment_ids"] != 0).astype(jnp.float32)}

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=None))
    state = TrainState.create(params, optimizer, jax.random.key(1))
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(rng.integers(1, 51, (4, 16)), jnp.int32),
        "targets": jnp.asarray(rng.integers(1, 51, (4, 16)), jnp.int32),
        "segment_ids": jnp.asarray(rng.integers(0, 3, (4, 16)), jnp.int32),
        "positions": jnp.asarray(np.tile(np.arange(16), (4, 1)), jnp.int32),
    }
    # The train state is consumed by the step (the trainer rebinds it);
    # an undonated buffer there is a dead full-model copy in HBM.
    return BuiltEntry(fn=step_fn, args=(state, batch), expect_donated=(0,))


if __name__ == "__main__":
    configlib.parse_config()
    train()
