"""RQ-VAE trainer (parity target: reference genrec/trainers/rqvae_trainer.py).

Loop shape mirrors the reference: epoch- or iteration-based (mutually
exclusive, :91-96), AdamW + linear-warmup schedule (:160-171), grad-clip
1.0, fixed gumbel temperature 0.2 (:215), ~20k-row k-means warmup before
step 0 (:218-228), eval = losses + collision rate over the full item set
(:26-47). Differences, by design:

- k-means warmup is an explicit seeded `kmeans_init_params` call, not a
  throwaway forward on a giant batch (deterministic across replicas,
  SURVEY.md §5.2);
- collision rate is computed on device via sort-unique, no host set();
- on exit the trainer exports the portable sem-id artifact that
  downstream TIGER/LCRec/COBRA datasets consume (data/sem_ids.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from genrec_tpu import configlib
from genrec_tpu.core.harness import jit_train_step, make_train_step
from genrec_tpu.core.logging import Tracker, setup_logger
from genrec_tpu.core.profiling import ProfileWindow
from genrec_tpu.core.state import TrainState
from genrec_tpu.data.batching import pad_to_batch
from genrec_tpu.data.items import ItemEmbeddingData, SyntheticItemEmbeddings
from genrec_tpu.data.sem_ids import save_sem_ids
from genrec_tpu.models.rqvae import (
    QuantizeForwardMode,
    RqVae,
    count_distinct,
    kmeans_init_params,
)
from genrec_tpu.ops.schedules import linear_schedule_with_warmup
from genrec_tpu.parallel import distributed_init, get_mesh, replicate


@functools.partial(jax.jit, static_argnums=0)
def _sem_ids_of(model, params, x):
    out = model.apply({"params": params}, x, 0.001, method=RqVae.get_semantic_ids)
    return out.sem_ids


@functools.partial(jax.jit, static_argnums=0)
def _sem_ids_of_pallas(model, params, x):
    """Encode with the MLP, then run the fused residual-cascade kernel
    (kernels/rq_cascade.py) — one VMEM-resident pass over all layers."""
    import jax.numpy as jnp

    from genrec_tpu.kernels.rq_cascade import rq_cascade_pallas

    enc = model.apply({"params": params}, x, method=RqVae.encode)
    codebooks = jnp.stack(
        [params[f"quantize_{l}"]["codebook"] for l in range(model.n_layers)]
    )
    ids, _ = rq_cascade_pallas(enc, codebooks)
    return ids


def compute_sem_ids(model, params, embeddings: np.ndarray, batch_size: int = 4096,
                    use_pallas: bool = False):
    """Semantic ids for every item (row i -> item id i+1). The jitted
    forward is cached on (model, shapes), so repeated evals don't
    recompile. The fused Pallas cascade (raw codebooks only — no sim_vq
    projection / normalization) is opt-in: measured on v5e the XLA path
    runs the cascade in 0.16ms vs the kernel's 1.49ms at B2048/K256 —
    XLA's own fusion wins at rqvae scales, so the kernel is kept
    validated (kernels/preflight.py) but off by default."""
    fused_ok = use_pallas and not (model.codebook_sim_vq or model.codebook_normalize)
    fn = _sem_ids_of_pallas if fused_ok else _sem_ids_of
    chunks = []
    for s in range(0, len(embeddings), batch_size):
        chunk = {"x": embeddings[s : s + batch_size]}
        n_real = len(chunk["x"])
        padded, _ = pad_to_batch(chunk, batch_size)
        chunks.append(np.asarray(fn(model, params, padded["x"]))[:n_real])
    return np.concatenate(chunks)


def compute_collision_rate(model, params, embeddings: np.ndarray):
    sem_ids = compute_sem_ids(model, params, embeddings)
    n = len(sem_ids)
    unique = int(count_distinct(jnp.asarray(sem_ids)))
    return (n - unique) / n, n, unique


@configlib.configurable
def train(
    epochs=None,
    iterations=None,
    warmup_epochs=0,
    warmup_iters=0,
    batch_size=1024,
    learning_rate=1e-3,
    weight_decay=1e-4,
    vae_input_dim=768,
    vae_n_cat_feats=0,
    vae_hidden_dims=(512, 256, 128, 64),
    vae_embed_dim=32,
    vae_codebook_size=256,
    vae_codebook_normalize=False,
    vae_sim_vq=False,
    vae_n_layers=3,
    vae_codebook_mode=QuantizeForwardMode.STE,
    vae_codebook_last_layer_mode=QuantizeForwardMode.SINKHORN,
    commitment_weight=0.25,
    gumbel_temperature=0.2,
    use_kmeans_init=True,
    kmeans_warmup_rows=20000,
    dataset="synthetic",
    dataset_folder="dataset/amazon",
    split="beauty",
    do_eval=True,
    eval_every=50,
    save_model_every=50,
    save_dir_root="out/rqvae",
    resume_from_checkpoint=False,
    sem_ids_path=None,
    wandb_logging=False,
    wandb_project="rqvae_training",
    wandb_log_interval=100,
    profile_steps=0,
    seed=0,
):
    if (epochs is None) == (iterations is None):
        raise ValueError("specify exactly one of 'epochs' or 'iterations'")
    use_epochs = epochs is not None

    distributed_init()
    logger = setup_logger(save_dir_root)
    tracker = Tracker(wandb_logging, wandb_project, save_dir=save_dir_root)
    mesh = get_mesh()

    if dataset == "synthetic":
        src = SyntheticItemEmbeddings(dim=vae_input_dim, seed=seed)
        train_x, eval_x = src.arrays()
        all_x = src.embeddings
    elif dataset == "p5":
        # Reference default source (P5AmazonReviewsItemDataset): items
        # filtered by the seed-42 train mask (p5_amazon.py:365-367).
        from genrec_tpu.data.p5_amazon import P5AmazonData, item_train_mask

        p5 = P5AmazonData(dataset_folder, split)
        all_x = p5.item_embeddings()  # one disk read
        mask = item_train_mask(len(all_x))
        train_x, eval_x = all_x[mask], all_x[~mask]
    else:
        src = ItemEmbeddingData(root=dataset_folder, split=split)
        train_x, eval_x = src.arrays()
        all_x = src.embeddings

    model = RqVae(
        input_dim=vae_input_dim,
        embed_dim=vae_embed_dim,
        hidden_dims=tuple(vae_hidden_dims),
        codebook_size=vae_codebook_size,
        codebook_normalize=vae_codebook_normalize,
        codebook_sim_vq=vae_sim_vq,
        codebook_mode=vae_codebook_mode,
        codebook_last_layer_mode=vae_codebook_last_layer_mode,
        n_layers=vae_n_layers,
        commitment_weight=commitment_weight,
        n_cat_features=vae_n_cat_feats,
    )

    rng = jax.random.key(seed)
    init_rng, km_rng, state_rng = jax.random.split(rng, 3)
    params = model.init(
        {"params": init_rng, "gumbel": init_rng},
        jnp.zeros((2, vae_input_dim), jnp.float32),
        0.2,
    )["params"]

    if use_kmeans_init:
        warm = train_x[:kmeans_warmup_rows]
        params = kmeans_init_params(model, params, jnp.asarray(warm), km_rng)
        logger.info(f"kmeans init on {len(warm)} rows")

    steps_per_epoch = max(1, len(train_x) // batch_size)
    if epochs is not None:
        total_steps = epochs * steps_per_epoch
        warmup_steps = warmup_epochs * steps_per_epoch
    else:
        total_steps = iterations
        warmup_steps = warmup_iters
        epochs = (iterations + steps_per_epoch - 1) // steps_per_epoch

    schedule = linear_schedule_with_warmup(learning_rate, warmup_steps, total_steps)
    optimizer = optax.adamw(schedule, weight_decay=weight_decay)

    def loss_fn(p, batch, step_rng):
        out = model.apply(
            {"params": p}, batch["x"], gumbel_temperature, training=True,
            rngs={"gumbel": step_rng},
        )
        return out.loss, {
            "reconstruction_loss": out.reconstruction_loss,
            "rqvae_loss": out.rqvae_loss,
            "p_unique_ids": out.p_unique_ids,
        }

    step_fn = jit_train_step(make_train_step(loss_fn, optimizer, clip_norm=1.0))
    state = replicate(mesh, TrainState.create(params, optimizer, state_rng))

    @jax.jit
    def eval_losses(p, x):
        out = model.apply({"params": p}, x, gumbel_temperature, training=False)
        return out.loss, out.reconstruction_loss, out.rqvae_loss

    from genrec_tpu.core.checkpoint import CheckpointManager
    from genrec_tpu.core.preemption import PreemptionGuard
    from genrec_tpu.trainers.packed_loop import PackedTrainLoop

    ckpt = CheckpointManager(os.path.join(save_dir_root, "checkpoints")) if save_dir_root else None
    prof = ProfileWindow(
        os.path.join(save_dir_root, "profile") if save_dir_root else "",
        profile_steps,
    )
    guard = PreemptionGuard(logger)

    def step_log(m, g):
        return {
            "global_step": g,
            "total_loss": float(m["loss"]),
            "reconstruction_loss": float(m["reconstruction_loss"]),
            "rqvae_loss": float(m["rqvae_loss"]),
            "p_unique_ids": float(m["p_unique_ids"]),
            "learning_rate": float(schedule(g)),
        }

    def step_hook(hook_state, epoch, next_batch, g):
        if use_epochs:
            return
        # Iteration mode gates eval/save on ITERATIONS (reference
        # rqvae_trainer.py:393,419), not derived epochs.
        if do_eval and g % eval_every == 0:
            le = eval_losses(hook_state.params, jnp.asarray(eval_x))
            cr, n, uniq = compute_collision_rate(model, hook_state.params, all_x)
            logger.info(
                f"iter {g} eval loss {float(le[0]):.4f} "
                f"collision {cr:.4f} ({uniq}/{n})"
            )
        if g % save_model_every == 0:
            loop.save(hook_state, epoch=epoch, next_batch=next_batch,
                      global_step=g)

    loop = PackedTrainLoop(
        logger=logger, tracker=tracker, prof=prof, mesh=mesh,
        guard=guard, ckpt=ckpt,
        rows_per_step=batch_size, row_len=1, seed=seed,
        pack_sequences=False, train_arrays={"x": train_x},
        wandb_log_interval=wandb_log_interval,
        save_dir_root=save_dir_root,
        step_log=step_log, step_hook=step_hook,
    )
    start_epoch, start_batch, global_step = 0, 0, 0
    if resume_from_checkpoint:
        # Step-granular exact resume (TrainState + data cursor through
        # the integrity ladder): continues at the exact next batch of a
        # possibly mid-epoch resume point.
        state, start_epoch, start_batch, global_step = loop.resume(
            state, lambda s: replicate(mesh, s)
        )
    for epoch in range(start_epoch, epochs):
        res = loop.run_epoch(
            state, step_fn, epoch, global_step,
            start_batch=start_batch if epoch == start_epoch else 0,
            max_steps=None if use_epochs else total_steps,
        )
        state, global_step = res.state, res.global_step
        if res.preempted:
            # SIGTERM/SIGINT grace window: the loop already wrote a
            # durable mid-epoch resume point; exit cleanly so the
            # scheduler restarts us with resume_from_checkpoint.
            loop.shutdown(preempted_epoch=epoch)
            return state.params, None

        if use_epochs and do_eval and ((epoch + 1) % eval_every == 0 or epoch + 1 == epochs):
            le = eval_losses(state.params, jnp.asarray(eval_x))
            cr, n, uniq = compute_collision_rate(model, state.params, all_x)
            logger.info(
                f"epoch {epoch+1} eval loss {float(le[0]):.4f} rec {float(le[1]):.4f} "
                f"vq {float(le[2]):.4f} collision {cr:.4f} ({uniq}/{n})"
            )
            tracker.log(
                {
                    "eval_total_loss": float(le[0]),
                    "eval_reconstruction_loss": float(le[1]),
                    "eval_rqvae_loss": float(le[2]),
                    "collision_rate": cr,
                    "unique_semantic_ids": uniq,
                }
            )

        if ckpt is not None and (
            (use_epochs and ((epoch + 1) % save_model_every == 0 or epoch + 1 == epochs))
            or (not use_epochs and epoch + 1 == epochs)
        ):
            # Epoch-boundary resume point (cursor = next epoch, batch 0):
            # one resumable step-keyed format everywhere, and the
            # unconditional final-epoch save means even a signal during
            # the LAST epoch's eval window leaves a resumable record.
            loop.save(state, epoch=epoch + 1, next_batch=0,
                      global_step=global_step)

    # Export the portable sem-id artifact for downstream stages.
    sem_ids = compute_sem_ids(model, state.params, all_x)
    out_path = sem_ids_path or os.path.join(save_dir_root, "sem_ids.npz")
    save_sem_ids(out_path, sem_ids, vae_codebook_size)
    logger.info(f"exported semantic ids -> {out_path}")
    loop.shutdown()
    return state.params, sem_ids


if __name__ == "__main__":
    configlib.parse_config()
    train()
